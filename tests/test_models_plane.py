"""Model control plane contract (CPU, tier-1 fast): the weight cache
evicts/spills/re-admits without changing a single output bit or paying
a recompile, the LRU order is the touch order, a hot reload under live
load loses zero admitted requests, the canary gates auto-roll-back a
fault-injected bad version, and shadow traffic is compared then
discarded — it never answers a client.

Uses LeNet (and the toy YOLO config where a second model is needed) at
random init: lifecycle correctness is about routing and residency, not
learned weights.  Runs with the lock-order sanitizer enabled (conftest
fixture keyed on the ``models`` marker), so every plane/cache lock
acquisition is order-checked.
"""

import threading
import time

import numpy as np
import pytest

from deep_vision_tpu.serve.admission import AdmissionController, Shed
from deep_vision_tpu.serve.engine import BatchingEngine
from deep_vision_tpu.serve.faults import FaultPlane, Quarantined
from deep_vision_tpu.serve.models import (ACTIVE, RETIRED, CanaryPolicy,
                                          ModelControlPlane, WeightCache)
from deep_vision_tpu.serve.registry import (CheckpointServingModel,
                                            ModelRegistry)

pytestmark = pytest.mark.models


def _engine_factory(model):
    """Small test engine; a model tagged ``_test_faults`` gets that
    fault spec with output validation OFF, so an injected-NaN "bad
    checkpoint" SERVES its NaNs for the canary gate to catch (the
    engine-level quarantine would otherwise eat them first)."""
    spec = getattr(model, "_test_faults", "")
    return BatchingEngine(model, buckets=[4], max_wait_ms=2,
                          faults=FaultPlane(spec),
                          validate_outputs=False if spec else None)


def _fresh_sm(sm):
    """A new ServingModel over the same weights — the reload loader
    seam's 'new checkpoint' stand-in (same cfg, fresh AOT dict)."""
    import types

    state = types.SimpleNamespace(
        params=sm._variables["params"],
        batch_stats=sm._variables.get("batch_stats"))
    new = CheckpointServingModel(sm.name, sm.cfg, sm._model, state)
    new.restored_step = (sm.restored_step or 0) + 1
    return new


@pytest.fixture()
def lenet_plane(tmp_path):
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "lenet_workdir"))
    cache = WeightCache(budget_bytes=0)
    plane = ModelControlPlane(
        reg, _engine_factory, cache=cache,
        policy=CanaryPolicy(canary_frac=0.5, min_requests=3,
                            max_p99_ratio=None, phase_timeout_s=15.0),
        admission_factory=lambda name: AdmissionController(name=name))
    plane.deploy(sm, workdir=str(tmp_path / "lenet_workdir"))
    yield reg, sm, plane, cache
    plane.stop()


def _img(shape=(32, 32, 1), seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


class _LoadThread(threading.Thread):
    """Continuous closed-loop client against one model name; collects
    every error (exception / Shed / Quarantined / NaN output) so reload
    tests can assert the zero-lost-requests contract."""

    def __init__(self, plane, name, img):
        super().__init__(daemon=True)
        self.plane, self.name, self.img = plane, name, img
        self.stop_flag = threading.Event()
        self.served = 0
        self.errors: list = []
        self.nan_outputs = 0

    def run(self):
        while not self.stop_flag.is_set():
            try:
                r = self.plane.infer(self.name, self.img, timeout=30)
            except Exception as e:  # noqa: BLE001 — every failure is a lost request
                self.errors.append(repr(e))
                continue
            if isinstance(r, (Shed, Quarantined)):
                self.errors.append(repr(r))
                continue
            if np.isnan(np.asarray(r)).any():
                self.nan_outputs += 1
            self.served += 1

    def finish(self):
        self.stop_flag.set()
        self.join(30)


# -- weight cache ----------------------------------------------------------


def test_evict_readmit_bit_identical_no_recompile(tmp_path):
    """A 1-byte budget forces every model switch through
    evict→spill→re-admit; outputs must stay bit-identical and the
    retained AOT programs must make re-admit compile-free."""
    reg = ModelRegistry()
    lenet = reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    yolo = reg.load_checkpoint("yolov3_toy", str(tmp_path / "y"))
    cache = WeightCache(budget_bytes=1)  # nothing fits: max thrash
    plane = ModelControlPlane(reg, _engine_factory, cache=cache)
    plane.deploy(lenet)
    plane.deploy(yolo)
    try:
        img = _img()
        first = np.asarray(plane.infer("lenet5", img, timeout=30))
        compiles = plane.active_engine("lenet5").compiles
        # serving yolo evicts lenet (budget holds neither; LRU loses)
        assert plane.infer(
            "yolov3_toy", _img((64, 64, 3)), timeout=30) is not None
        assert "lenet5" not in cache.resident_models()
        again = np.asarray(plane.infer("lenet5", img, timeout=30))
        assert np.array_equal(first, again)  # bit-identical round trip
        assert plane.active_engine("lenet5").compiles == compiles
        st = cache.stats()
        assert st["evictions"] >= 2 and st["admits"] >= 1
        assert st["spilled_bytes_total"] > 0
        assert st["models"]["lenet5"]["spilled"]
    finally:
        plane.stop()


def test_lru_order_is_touch_order():
    """3 models, budget = 2: residency follows recency, not insertion."""
    import jax

    class _Fake:
        def __init__(self, name):
            self.name = name
            self._variables = {"w": jax.device_put(
                np.full(256, 1.0, np.float32))}
            self._var_sharding = None
            self._cache = None

    a, b, c = _Fake("a"), _Fake("b"), _Fake("c")
    nbytes = 256 * 4
    cache = WeightCache(budget_bytes=2 * nbytes)
    for m in (a, b, c):
        cache.register(m)  # admitting c evicts a (the LRU resident)
    assert sorted(cache.resident_models()) == ["b", "c"]
    assert cache.variables_for(b) is not None   # touch b: order is c,b
    assert cache.variables_for(a) is not None   # admit a → evict c
    assert sorted(cache.resident_models()) == ["a", "b"]
    st = cache.stats()
    assert st["evictions"] == 2 and st["hits"] == 1 and st["misses"] == 1
    # dropped models leave the table entirely, bytes included
    cache.drop(a)
    assert "a" not in cache.stats()["models"]


def test_oversized_model_still_serves_over_budget(tmp_path):
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    cache = WeightCache(budget_bytes=1)
    plane = ModelControlPlane(reg, _engine_factory, cache=cache)
    plane.deploy(sm)
    try:
        assert plane.infer("lenet5", _img(), timeout=30) is not None
        assert cache.stats()["over_budget"] >= 1
    finally:
        plane.stop()


# -- hot reload ------------------------------------------------------------


@pytest.mark.chaos
def test_hot_reload_under_load_loses_zero_requests(lenet_plane):
    """The zero-downtime contract: a reload (load → canary → promote →
    drain old) under continuous live load answers every request — no
    shutdown sheds leak to clients (raced requests resubmit), no
    errors, and the new version ends ACTIVE."""
    _, sm, plane, _ = lenet_plane
    load = _LoadThread(plane, "lenet5", _img())
    load.start()
    while load.served < 5:  # engine warm + traffic flowing
        time.sleep(0.01)
    out = plane.reload("lenet5", wait=True,
                       _loader=lambda: _fresh_sm(sm))
    load.finish()
    assert out["status"] == "done"
    assert out["version"]["state"] == ACTIVE
    assert out["version"]["version"] == 2
    assert load.errors == []  # ZERO lost requests
    assert load.nan_outputs == 0
    assert load.served > 0
    st = plane.stats()
    assert st["plane"]["promotions"] == 1
    assert st["models"]["lenet5"]["active_version"] == 2
    # the old version drained and retired; its cohort finished on it
    states = [v["state"] for v in st["models"]["lenet5"]["versions"]]
    assert states == [RETIRED, ACTIVE]


@pytest.mark.chaos
def test_canary_rolls_back_nan_bad_version(lenet_plane):
    """A fault-injected bad candidate (d2h:nan — the bad-checkpoint
    signature) fails the canary error-rate gate and auto-rolls-back;
    v1 keeps serving and post-rollback outputs are NaN-free."""
    _, sm, plane, _ = lenet_plane

    def bad_loader():
        new = _fresh_sm(sm)
        new._test_faults = "d2h:nan"  # engine factory serves the NaNs
        return new

    load = _LoadThread(plane, "lenet5", _img())
    load.start()
    while load.served < 5:
        time.sleep(0.01)
    out = plane.reload("lenet5", wait=True, _loader=bad_loader)
    load.finish()
    assert out["status"] == "done"
    assert out["version"]["version"] == 2
    assert out["version"]["state"] == RETIRED
    assert "canary error rate" in out["version"]["state_reason"]
    st = plane.stats()
    assert st["plane"]["rollbacks"] == 1
    assert st["plane"]["promotions"] == 0
    assert st["models"]["lenet5"]["active_version"] == 1  # v1 survived
    r = np.asarray(plane.infer("lenet5", _img(), timeout=30))
    assert not np.isnan(r).any()


@pytest.mark.chaos
def test_canary_p99_gate_rolls_back_slow_version(tmp_path):
    """A candidate 100x slower than the active (injected d2h latency)
    trips a rollback gate even though its answers are correct."""
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    plane = ModelControlPlane(
        reg, _engine_factory,
        policy=CanaryPolicy(canary_frac=0.5, min_requests=3,
                            max_error_rate=1.0, max_p99_ratio=3.0,
                            phase_timeout_s=20.0))
    plane.deploy(sm)
    plane.warmup()  # keep the compile out of the active's p99 history

    def slow_loader():
        new = _fresh_sm(sm)
        new._test_faults = "d2h:latency:delay_ms=300"
        return new

    load = _LoadThread(plane, "lenet5", _img())
    load.start()
    try:
        while load.served < 10:  # active builds latency history
            time.sleep(0.01)
        out = plane.reload("lenet5", wait=True, _loader=slow_loader)
        assert out["status"] == "done"
        assert out["version"]["state"] == RETIRED
        assert plane.stats()["plane"]["rollbacks"] == 1
        assert plane.stats()["models"]["lenet5"]["active_version"] == 1
    finally:
        load.finish()
        plane.stop()


def test_shadow_compares_then_discards(lenet_plane):
    """Shadow phase: the candidate sees duplicated live traffic, top-1
    agreement is recorded, and every shadow output is discarded — each
    client request resolves exactly once, from the primary."""
    _, sm, plane, _ = lenet_plane
    plane.policy = CanaryPolicy(canary_frac=0.5, min_requests=3,
                                shadow_frac=1.0, shadow_min_compared=3,
                                min_agreement=0.8, max_p99_ratio=None,
                                phase_timeout_s=15.0)
    load = _LoadThread(plane, "lenet5", _img())
    load.start()
    while load.served < 5:
        time.sleep(0.01)
    out = plane.reload("lenet5", wait=True,
                       _loader=lambda: _fresh_sm(sm))
    load.finish()
    assert out["status"] == "done"
    assert out["version"]["state"] == ACTIVE  # identical weights agree
    shadow = out["version"]["shadow"]
    assert shadow["compared"] >= 3
    assert shadow["agreed"] == shadow["compared"]  # same weights
    assert shadow["discarded"] >= shadow["compared"]
    assert load.errors == []  # duplication never double-answers


def _wait_for_state(plane, name, version, state, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for v in plane.models()[name]["versions"]:
            if v["version"] == version and v["state"] == state:
                return True
        time.sleep(0.01)
    return False


def _join_reload(plane, name, timeout=20.0):
    t = plane._reloading.get(name)  # the worker thread (test-only peek)
    if t is not None:
        t.join(timeout)
        assert not t.is_alive()


@pytest.mark.chaos
def test_operator_promote_wins_over_worker_rollback(tmp_path):
    """An operator promote mid-CANARY is final: the background worker —
    whose phase would otherwise time out and roll back — must stand
    down, NOT retire the now-ACTIVE version.  (The regression this
    pins: the worker's rollback used to retire the promoted version,
    leaving _active pointing at a stopped engine.)"""
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    plane = ModelControlPlane(
        reg, _engine_factory,
        policy=CanaryPolicy(canary_frac=0.5, min_requests=10**6,
                            max_p99_ratio=None, phase_timeout_s=30.0))
    plane.deploy(sm)
    try:
        out = plane.reload("lenet5", _loader=lambda: _fresh_sm(sm))
        assert out["status"] == "reloading"
        assert _wait_for_state(plane, "lenet5", 2, "canary")
        res = plane.promote("lenet5")
        assert res == {"status": "promoted", "model": "lenet5",
                       "version": 2}
        _join_reload(plane, "lenet5")
        st = plane.stats()
        assert st["models"]["lenet5"]["active_version"] == 2
        assert st["plane"]["promotions"] == 1
        assert st["plane"]["rollbacks"] == 0  # worker stood down
        states = {v["version"]: v["state"]
                  for v in st["models"]["lenet5"]["versions"]}
        assert states == {1: RETIRED, 2: ACTIVE}
        # the promoted version actually answers — its engine never
        # stopped, and v2 is queryable through the registry
        r = plane.infer("lenet5", _img(), timeout=30)
        assert not isinstance(r, (Shed, Quarantined))
        assert reg.get("lenet5", version=2) is not None
        # a second promote finds nothing in flight
        assert plane.promote("lenet5")["status"] == "refused"
    finally:
        plane.stop()


@pytest.mark.chaos
def test_operator_rollback_wins_over_worker_promote(tmp_path):
    """The symmetric race: after an operator rollback mid-SHADOW the
    worker must not promote the retired candidate (a stopped engine
    must never become the active route)."""
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    plane = ModelControlPlane(
        reg, _engine_factory,
        policy=CanaryPolicy(canary_frac=0.5, min_requests=1,
                            shadow_frac=1.0,
                            shadow_min_compared=10**6,
                            max_p99_ratio=None, phase_timeout_s=30.0))
    plane.deploy(sm)
    try:
        out = plane.reload("lenet5", _loader=lambda: _fresh_sm(sm))
        assert out["status"] == "reloading"
        assert _wait_for_state(plane, "lenet5", 2, "shadow")
        res = plane.rollback("lenet5")
        assert res == {"status": "rolled_back", "model": "lenet5",
                       "version": 2}
        _join_reload(plane, "lenet5")
        st = plane.stats()
        assert st["models"]["lenet5"]["active_version"] == 1
        assert st["plane"]["promotions"] == 0  # worker did NOT promote
        assert st["plane"]["rollbacks"] == 1
        states = {v["version"]: v["state"]
                  for v in st["models"]["lenet5"]["versions"]}
        assert states == {1: ACTIVE, 2: RETIRED}
        assert st["models"]["lenet5"]["versions"][-1]["state_reason"] \
            == "operator rollback"
        r = plane.infer("lenet5", _img(), timeout=30)
        assert not isinstance(r, (Shed, Quarantined))
    finally:
        plane.stop()


def test_retired_version_releases_weights_and_prunes_registry(tmp_path):
    """Repeated reloads must not pin one HBM weight copy per retired
    version: a retired version's variables move to host numpy, and
    versions pruned past ``retain_retired`` also leave the registry's
    version table."""
    import jax

    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    plane = ModelControlPlane(
        reg, _engine_factory,
        policy=CanaryPolicy(canary_frac=0.5, min_requests=1,
                            max_p99_ratio=None, phase_timeout_s=15.0),
        retain_retired=1)
    plane.deploy(sm)
    load = _LoadThread(plane, "lenet5", _img())
    load.start()
    try:
        while load.served < 3:
            time.sleep(0.01)
        sm2 = _fresh_sm(sm)
        out = plane.reload("lenet5", wait=True, _loader=lambda: sm2)
        assert out["version"]["state"] == ACTIVE
        # retired v1 spilled to host: no leaf is a device array
        leaves = jax.tree_util.tree_leaves(sm._variables)
        assert leaves
        assert all(isinstance(a, np.ndarray) for a in leaves)
        # ...while the active v2 stays device-backed and serving
        assert any(isinstance(a, jax.Array) for a in
                   jax.tree_util.tree_leaves(sm2._variables))
        out = plane.reload("lenet5", wait=True,
                           _loader=lambda: _fresh_sm(sm2))
        assert out["version"]["version"] == 3
        assert out["version"]["state"] == ACTIVE
        # retain_retired=1 keeps only v2's corpse: v1 left the table
        # AND the registry's version index
        versions = [v["version"] for v in
                    plane.models()["lenet5"]["versions"]]
        assert 1 not in versions and versions[-1] == 3
        with pytest.raises(KeyError):
            reg.get("lenet5", version=1)
        assert reg.get("lenet5", version=2) is sm2
        assert load.errors == []
    finally:
        load.finish()
        plane.stop()


def test_deploy_failure_leaves_no_table_entry(tmp_path):
    """A deploy whose engine fails to start must not leak a LOADING
    version into the table (and the next deploy reuses the number)."""
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "l"))

    class _BoomEngine:
        def start(self):
            raise RuntimeError("boom")

    plane = ModelControlPlane(reg, lambda m: _BoomEngine())
    with pytest.raises(RuntimeError):
        plane.deploy(sm)
    listing = plane.models().get("lenet5", {})
    assert listing.get("versions", []) == []
    assert listing.get("active_version") is None
    plane2 = ModelControlPlane(reg, _engine_factory)
    mv = plane2.deploy(sm)
    try:
        assert mv.version == 1
    finally:
        plane2.stop()


def test_reload_refused_without_workdir_and_while_in_progress(tmp_path):
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    plane = ModelControlPlane(reg, _engine_factory)
    plane.deploy(sm)  # no workdir
    try:
        out = plane.reload("lenet5")
        assert out["status"] == "refused"
        assert "workdir" in out["reason"]
        with pytest.raises(KeyError):
            plane.reload("nope")
    finally:
        plane.stop()


# -- satellites ------------------------------------------------------------


def test_registry_get_requires_name_with_multiple_models(tmp_path):
    reg = ModelRegistry()
    reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    reg.load_checkpoint("yolov3_toy", str(tmp_path / "y"))
    with pytest.raises(KeyError) as exc:
        reg.get(None)
    msg = exc.value.args[0]  # args[0], NOT str(): no doubled quotes
    assert msg.startswith("model name required")
    assert "lenet5" in msg and "yolov3_toy" in msg
    assert not msg.startswith('"')


def test_registry_versioned_get(tmp_path):
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    sm.serve_version = 1
    reg.add(sm, version=1)
    assert reg.get("lenet5", version=1) is sm
    with pytest.raises(KeyError) as exc:
        reg.get("lenet5", version=99)
    assert "no version 99" in exc.value.args[0]


def test_restore_stamps_mtime_and_digest(tmp_path):
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import (checkpoint_fingerprint,
                                              load_state)

    fp = checkpoint_fingerprint(str(tmp_path))  # no checkpoints yet
    assert fp["step"] is None
    info: dict = {}
    load_state(get_config("lenet5"), str(tmp_path), info=info)
    assert info["digest"] is not None  # digest even for random init
    assert "mtime" in info
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path))
    d = sm.describe()
    assert d["params_digest"] == sm.params_digest is not None
    assert "restored_mtime" in d


def test_admitted_counter_and_named_admission(tmp_path):
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    adm = AdmissionController(name="lenet5")
    with BatchingEngine(sm, buckets=[4], max_wait_ms=2,
                        admission=adm) as eng:
        for _ in range(3):
            assert eng.infer(_img(), timeout=30) is not None
        st = eng.stats()["admission"]
    assert st["admitted"] == 3
    assert st["name"] == "lenet5"


def test_http_models_lifecycle_and_metrics(lenet_plane):
    """/v1/models listing, lifecycle endpoints (404 uses the KeyError
    payload unquoted, no-candidate promote answers 409), plane-shaped
    /v1/stats, and the model/cache Prometheus series."""
    import json
    import urllib.error
    import urllib.request

    from deep_vision_tpu.serve.http import ServeServer

    reg, sm, plane, _ = lenet_plane
    srv = ServeServer(reg, plane.active_engines(), port=0,
                      plane=plane).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(base + "/v1/models") as r:
            listing = json.loads(r.read())["models"]
        assert listing["lenet5"]["active_version"] == 1
        assert listing["lenet5"]["versions"][0]["state"] == ACTIVE
        with urllib.request.urlopen(base + "/v1/stats") as r:
            stats = json.loads(r.read())
        assert set(stats) >= {"models", "cache", "plane"}
        body = json.dumps({"pixels": np.zeros((32, 32, 1)).tolist()})
        req = urllib.request.Request(
            base + "/v1/models/lenet5/classify", data=body.encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            assert len(json.loads(r.read())["top"]) == 5
        # unknown model on the path: 404, message straight from
        # KeyError.args[0] — no doubled quotes from str(KeyError)
        req = urllib.request.Request(
            base + "/v1/models/nope/reload", data=b"{}",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 404
        err = json.loads(exc.value.read())["error"]
        assert err.startswith("unknown model")
        assert not err.startswith('"')
        # promote with no candidate in flight: 409, not 200
        req = urllib.request.Request(
            base + "/v1/models/lenet5/promote", data=b"{}",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 409
        with urllib.request.urlopen(base + "/metrics") as r:
            text = r.read().decode()
        assert 'dvt_serve_model_up{model="lenet5"' in text
        assert "dvt_serve_weight_cache_hits_total" in text
        assert "dvt_serve_reloads_total" in text
    finally:
        srv.shutdown()


def test_http_reload_endpoint_full_cycle(lenet_plane):
    """POST /v1/models/lenet5/reload {force, wait} under live load:
    200 with the promoted version in the body."""
    import json
    import urllib.request

    from deep_vision_tpu.serve.http import ServeServer

    reg, sm, plane, _ = lenet_plane
    srv = ServeServer(reg, plane.active_engines(), port=0,
                      plane=plane).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    load = _LoadThread(plane, "lenet5", _img())
    load.start()
    try:
        while load.served < 5:
            time.sleep(0.01)
        req = urllib.request.Request(
            base + "/v1/models/lenet5/reload",
            data=json.dumps({"force": True, "wait": True}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read())
        assert out["status"] == "done"
        assert out["version"]["version"] == 2
        assert out["version"]["state"] == ACTIVE
        assert load.errors == []
    finally:
        load.finish()
        srv.shutdown()
