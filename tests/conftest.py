"""Test env: 8 virtual CPU devices so pmap/pjit/mesh paths are exercised
without a pod (SURVEY §4 implication (d))."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The image's sitecustomize force-registers the TPU ('axon') platform and
# overrides JAX_PLATFORMS, so pin CPU via config (must run before any
# backend init).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from deep_vision_tpu.parallel import make_mesh

    return make_mesh({"data": 8})


@pytest.fixture(scope="session")
def mesh1():
    from deep_vision_tpu.parallel import make_mesh

    return make_mesh({"data": 1}, devices=jax.devices()[:1])


@pytest.fixture(scope="session")
def host_devices():
    """The 8 forced host devices multi-device serving tests replicate
    and shard over (tests/test_replicas.py)."""
    devs = jax.local_devices()
    assert len(devs) >= 8, f"expected 8 forced host devices, got {devs}"
    return devs


# The dvtlint runtime half (docs/ANALYSIS.md): every chaos/gateway/replicas
# test runs with DVT_LOCK_SANITIZER semantics on — serve/* locks become
# SanitizedLocks recording acquisition order, and the test FAILS at teardown
# if any thread observed a lock-order inversion (even one a worker thread
# swallowed). Engines/gateways are constructed inside the tests, after this
# fixture enables the seam, so every lock they create is instrumented.
_SANITIZED_MARKERS = {"chaos", "gateway", "replicas", "models", "deploy",
                      "edge", "mesh", "batch"}


@pytest.fixture(autouse=True)
def _dvt_lock_sanitizer(request):
    from deep_vision_tpu.analysis import sanitizer

    if not (_SANITIZED_MARKERS
            & {m.name for m in request.node.iter_markers()}):
        yield
        return
    was = sanitizer.enabled()
    sanitizer.enable(True)
    sanitizer.reset()
    try:
        yield
        violations = sanitizer.violations()
        assert not violations, (
            "lock-order violations during test:\n  " + "\n  ".join(violations))
    finally:
        sanitizer.reset()
        sanitizer.enable(was)
