"""Chaos suite (CPU, tier-1 fast, deterministic under the fixed seed):
the serving engine's FAILURE paths are tested paths — crash-only style.

Every scenario drives a real engine through the in-tree fault plane
(``serve/faults.py``) and asserts the recovery contract:

  * a poisoned request is quarantined by bisect-retry while every
    innocent cohort member is served the same bits it would have gotten
    in a clean batch;
  * a transient batch failure is retried to success and the state
    machine returns to OK;
  * ``/v1/healthz`` flips 200 → 503 → 200 around a failure, so a load
    balancer would drain and readmit this replica at the right moments;
  * a killed worker thread is restarted by the watchdog and traffic
    resumes;
  * a hung drain is fast-failed at the exec timeout instead of parking
    its futures for the hang's full duration;
  * lifecycle misuse (submit before start / after stop) fails fast;
  * oversized HTTP bodies bounce 413 before allocation;
  * a corrupt newest checkpoint falls back to the previous retained
    step (``core/restore.py``).

Run alone via ``make serve-chaos`` (``pytest -m chaos``)."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deep_vision_tpu.serve.admission import Shed
from deep_vision_tpu.serve.engine import BatchingEngine
from deep_vision_tpu.serve.faults import (
    FaultPlane,
    InjectedFault,
    Quarantined,
    parse_faults,
)
from deep_vision_tpu.serve.registry import ModelRegistry

pytestmark = [pytest.mark.serve, pytest.mark.chaos]


@pytest.fixture(scope="module")
def lenet_serving(tmp_path_factory):
    reg = ModelRegistry()
    # empty workdir fixture → deterministic PRNGKey(0) random init
    sm = reg.load_checkpoint(
        "lenet5", str(tmp_path_factory.mktemp("lenet_workdir")))
    return reg, sm


def _images(n, shape=(32, 32, 1)):
    return [np.random.RandomState(i).randn(*shape).astype(np.float32)
            for i in range(n)]


def _wait_until(cond, timeout=10.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# -- fault plane unit behavior ---------------------------------------------


def test_fault_spec_parse():
    faults = parse_faults(
        "compute:poison:nth=3;d2h:latency:delay_ms=20;"
        "batcher:die:times=1:after=2")
    assert [(f.stage, f.mode) for f in faults] == \
        [("compute", "poison"), ("d2h", "latency"), ("batcher", "die")]
    assert faults[0].nth == 3
    assert faults[1].delay_ms == 20.0
    assert faults[2].times == 1 and faults[2].after == 2
    assert parse_faults("") == [] and parse_faults(None) == []
    for bad in ("compute", "nowhere:exception", "compute:explode",
                "compute:exception:bogus=1", "compute:exception:times"):
        with pytest.raises(ValueError):
            parse_faults(bad)


def test_fault_plane_deterministic_under_seed():
    def firing_pattern(seed):
        plane = FaultPlane("compute:exception:p=0.5", seed)
        pattern = []
        for _ in range(64):
            try:
                plane.inject("compute")
                pattern.append(False)
            except InjectedFault:
                pattern.append(True)
        return pattern

    a, b = firing_pattern(7), firing_pattern(7)
    assert a == b  # same seed → identical firing sequence
    assert True in a and False in a  # p=0.5 actually mixes
    assert firing_pattern(8) != a  # and the seed matters


def test_fault_plane_disabled_is_inert():
    plane = FaultPlane("")
    assert not plane.enabled
    assert plane.inject("compute") is None
    assert plane.mark_poison() is False


# -- batch-failure isolation -----------------------------------------------


def test_poison_request_quarantined_innocents_served(lenet_serving):
    """A cohort of 8 with one poison member: bisect-retry converges on
    exactly the poisoned request; the other 7 get the same bits a clean
    batch would have produced."""
    _, sm = lenet_serving
    imgs = _images(8)
    with BatchingEngine(sm, buckets=[8], max_wait_ms=250,
                        faults=FaultPlane("compute:poison:nth=3"),
                        retry_backoff_ms=0) as eng:
        futures = [eng.submit(im) for im in imgs]
        results = [f.result(60) for f in futures]
    assert isinstance(results[3], Quarantined)
    assert results[3].reason == "poison"
    assert not results[3]  # falsy, like Shed: `if result:` = "served"
    ref = np.asarray(sm.compile_bucket(8)(np.stack(imgs)))
    for i in (0, 1, 2, 4, 5, 6, 7):
        assert np.array_equal(np.asarray(results[i]), ref[i]), i
    assert eng.quarantined == 1
    assert eng.batch_failures == 1  # ONE original cohort failure
    assert eng.retry_executions >= 3  # bisection actually bisected
    assert eng.served == 7


def test_transient_failure_retried_to_success(lenet_serving):
    """One injected compute exception: the split cohorts re-execute
    cleanly, every request is served, and health returns to OK."""
    _, sm = lenet_serving
    imgs = _images(4)
    with BatchingEngine(sm, buckets=[4], max_wait_ms=250,
                        faults=FaultPlane("compute:exception:times=1"),
                        retry_backoff_ms=0) as eng:
        futures = [eng.submit(im) for im in imgs]
        results = [f.result(60) for f in futures]
        report = eng.health_report()
    ref = np.asarray(sm.compile_bucket(4)(np.stack(imgs)))
    for i in range(4):
        assert np.array_equal(np.asarray(results[i]), ref[i]), i
    assert eng.batch_failures == 1
    assert eng.retry_executions == 2  # two halves, each clean
    assert eng.quarantined == 0
    assert report["state"] == "ok"  # success reset the state machine
    assert report["faults"]["injected"] == {"compute:exception": 1}


# -- deep health over HTTP --------------------------------------------------


def test_healthz_flips_200_503_200(lenet_serving):
    """The load-balancer contract: healthy 200 → failure flips 503
    (drain me) → first good batch flips back 200 (readmit me)."""
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[1], max_wait_ms=1,
                         faults=FaultPlane("compute:exception:times=1"),
                         degraded_after=1, singleton_retries=0,
                         retry_backoff_ms=0).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    body = json.dumps({"pixels": np.zeros((32, 32, 1)).tolist()}).encode()

    def healthz():
        try:
            with urllib.request.urlopen(base + "/v1/healthz") as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def classify():
        req = urllib.request.Request(
            base + "/v1/classify", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    try:
        status, payload = healthz()
        assert status == 200 and payload["status"] == "ok"
        # singleton_retries=0: the injected failure quarantines the lone
        # request (500) and leaves the engine DEGRADED — no success yet
        assert classify() == 500
        status, payload = healthz()
        assert status == 503
        rep = payload["engines"]["lenet5"]
        assert rep["state"] == "degraded"
        assert rep["quarantined"] == 1
        # the injection is exhausted: the next batch succeeds and the
        # state machine (and the probe) recover on their own
        assert classify() == 200
        status, payload = healthz()
        assert status == 200
        assert payload["engines"]["lenet5"]["state"] == "ok"
    finally:
        srv.shutdown()
        eng.stop()


# -- watchdog supervision ---------------------------------------------------


def test_batcher_killed_then_restarted(lenet_serving):
    """mode=die kills the batcher thread; the watchdog restarts it and
    traffic resumes without operator action."""
    _, sm = lenet_serving
    img = _images(1)[0]
    with BatchingEngine(sm, buckets=[1], max_wait_ms=1,
                        faults=FaultPlane("batcher:die:times=1"),
                        watchdog_interval_s=0.01) as eng:
        assert _wait_until(
            lambda: eng.health.watchdog_restarts >= 1), \
            "watchdog never restarted the dead batcher"
        result = eng.infer(img, timeout=60)  # served by the NEW thread
        assert result is not None and not isinstance(result, Shed)
        report = eng.health_report()
    assert report["watchdog_restarts"] >= 1
    assert report["batcher_alive"]
    assert report["state"] == "ok"  # the served batch reset the machine
    assert report["faults"]["injected"] == {"batcher:die": 1}


def test_restart_budget_exhaustion_is_sticky_dead(lenet_serving):
    """A thread that keeps dying burns the restart budget and the engine
    goes sticky-DEAD — traffic can't revive it, only a stop/start."""
    _, sm = lenet_serving
    with BatchingEngine(sm, buckets=[1], max_wait_ms=1,
                        faults=FaultPlane("batcher:die"),  # every time
                        watchdog_interval_s=0.01,
                        restart_budget=2) as eng:
        assert _wait_until(lambda: eng.health.state == "dead"), \
            "restart-budget exhaustion never forced DEAD"
        report = eng.health_report()
        assert report["watchdog_restarts"] == 2
        assert "restart budget" in report["dead_reason"]


def test_hang_is_fast_failed_at_exec_timeout(lenet_serving):
    """An injected 30 s hang in the drain path: the watchdog fails the
    in-flight window at the ~0.2 s exec timeout, so the caller sees a
    fast TimeoutError — and the next request is served normally."""
    _, sm = lenet_serving
    img = _images(1)[0]
    with BatchingEngine(sm, buckets=[1], max_wait_ms=1, pipeline_depth=2,
                        faults=FaultPlane("d2h:hang:hang_s=30:times=1"),
                        watchdog_interval_s=0.02,
                        exec_timeout_min_s=0.2) as eng:
        t0 = time.monotonic()
        fut = eng.submit(img)
        with pytest.raises(TimeoutError):
            fut.result(20)
        assert time.monotonic() - t0 < 5.0  # vastly under the 30 s hang
        assert eng.exec_timeouts == 1
        # hang exhausted (times=1): the engine recovers by itself
        result = eng.infer(img, timeout=60)
        assert result is not None and not isinstance(result, Shed)
        assert eng.health_report()["state"] == "ok"


# -- lifecycle --------------------------------------------------------------


def test_submit_outside_lifecycle_fails_fast(lenet_serving):
    _, sm = lenet_serving
    img = _images(1)[0]
    eng = BatchingEngine(sm, buckets=[1])
    before = eng.submit(img).result(1)  # before start()
    assert isinstance(before, Shed) and before.reason == "shutdown"
    eng.start()
    assert eng.infer(img, timeout=60) is not None
    eng.stop()
    after = eng.submit(img).result(1)  # after stop()
    assert isinstance(after, Shed) and after.reason == "shutdown"
    assert eng.shed_shutdown == 2


def test_stop_drain_deadline_finishes_admitted_work(lenet_serving):
    """stop(drain_deadline=...) rejects new submits immediately but
    serves everything already admitted before tearing down."""
    _, sm = lenet_serving
    imgs = _images(4)
    eng = BatchingEngine(sm, buckets=[4], max_wait_ms=20).start()
    eng.warmup()
    futures = [eng.submit(im) for im in imgs]
    eng.stop(drain_deadline=30.0)
    results = [f.result(1) for f in futures]  # already resolved
    assert all(r is not None and not isinstance(r, Shed)
               for r in results)
    assert eng.served == 4


# -- HTTP body cap ----------------------------------------------------------


def test_oversized_body_rejected_413(lenet_serving):
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[1], max_wait_ms=1).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0,
                      max_body_bytes=1024).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        body = b'{"pixels": [' + b"0," * 4096 + b"0]}"
        req = urllib.request.Request(
            base + "/v1/classify", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=60)
        assert exc.value.code == 413
        # the rejection closed that connection without wedging the
        # server: a fresh in-cap request still answers
        with urllib.request.urlopen(base + "/v1/healthz",
                                    timeout=60) as r:
            assert r.status == 200
        assert eng.served == 0  # the oversized body never reached it
    finally:
        srv.shutdown()
        eng.stop()


# -- checkpoint restore fallback -------------------------------------------


def test_restore_falls_back_past_corrupt_step(tmp_path):
    """Save steps 1 and 2 with distinguishable params, corrupt step 2 on
    disk: load_state restores step 1 and reports the fallback."""
    import os

    import jax

    from deep_vision_tpu.core import checkpoint as ckpt_lib
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import load_state

    workdir = str(tmp_path / "wd")
    cfg = get_config("lenet5")
    logs: list = []
    _, state = load_state(cfg, workdir, log=logs.append)  # fresh init
    bumped = state.replace(params=jax.tree_util.tree_map(
        lambda a: a + 1.0, state.params))
    ckpt = ckpt_lib.Checkpointer(os.path.join(workdir, "checkpoints"))
    ckpt.save(1, state)
    ckpt.save(2, bumped)
    ckpt.close()
    # corrupt step 2 in place: garbage in every file, dir still listed
    step2 = os.path.join(workdir, "checkpoints", "2")
    for root, _, files in os.walk(step2):
        for f in files:
            with open(os.path.join(root, f), "wb") as fh:
                fh.write(b"\x00corrupt\x00")
    ckpt2 = ckpt_lib.Checkpointer(os.path.join(workdir, "checkpoints"))
    assert 2 in ckpt2.all_steps()  # still retained — restore must fail it
    ckpt2.close()

    info: dict = {}
    logs.clear()
    _, restored = load_state(cfg, workdir, log=logs.append, info=info)
    assert info["step"] == 1
    assert info["fallback"] is True
    leaf = jax.tree_util.tree_leaves(restored.params)[0]
    want = jax.tree_util.tree_leaves(state.params)[0]
    assert np.allclose(np.asarray(leaf), np.asarray(want))  # step 1 bits
    assert any("falling back" in m for m in logs)
    assert any("FALLBACK" in m for m in logs)

    # the registry surfaces which step actually backs the served model
    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", workdir)
    assert sm.restored_step == 1 and sm.restore_fallback is True
    assert sm.describe()["restore_fallback"] is True
