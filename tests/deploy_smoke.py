"""`make deploy-smoke`: the hands-off train→deploy loop, end to end
over real HTTP.  Boots `cli.serve --models lenet5 --watch` wiring
(build_server's plane path + DeployPipeline), then, while a client
thread hammers /v1/models/lenet5/classify the whole time:

  * writes a REAL async-Orbax checkpoint (step 1) into the watched
    workdir mid-load — the watcher must fingerprint it, debounce it,
    pass it through the accuracy gate (fresh random init under
    PRNGKey(0) is byte-identical to the serving weights, so agreement
    is 1.0), and roll it through canary → promote to v2 with ZERO
    client errors and no operator action;
  * writes a NaN-params checkpoint (step 2) — the gate must refuse it
    (a gate_failed ledger record), and v2 must keep serving;
  * POSTs /v1/deploy/lenet5/revert — one command back to the previous
    promoted version (v3 wraps v1's weights), still zero client errors;
  * asserts GET /v1/deploy/lenet5/history tells exactly that story,
    /v1/stats carries the deploy block, and /metrics exposes the
    dvt_deploy_* and dvt_serve_reverts_total series as parseable
    Prometheus text.

Run directly, not under pytest."""

import argparse
import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/deploy_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_PROM_LINE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? (\S+)$")


def _get(base, path, timeout=60):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _post(base, path, payload=None, timeout=120):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode() if payload is not None else b"",
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _wait_for(what, predicate, deadline_s=90.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        out = predicate()
        if out is not None:
            return out
        time.sleep(0.1)
    raise AssertionError(f"timed out after {deadline_s}s waiting for {what}")


def smoke():
    import jax

    from deep_vision_tpu.cli.serve import build_server
    from deep_vision_tpu.core.checkpoint import Checkpointer
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import load_state

    with tempfile.TemporaryDirectory() as workdir:
        os.makedirs(os.path.join(workdir, "lenet5"), exist_ok=True)
        args = argparse.Namespace(
            model=None, models="lenet5", workdir=workdir,
            stablehlo=None, host="127.0.0.1", port=0, max_batch=4,
            max_wait_ms=2.0, buckets=None, max_queue=64, warmup=True,
            verbose=False, pipeline_depth=2, faults="", fault_seed=0,
            serve_devices=1, shard_batches=False, wire_dtype="float32",
            infer_dtype="float32", hbm_budget_mb=0.0,
            canary_frac=0.5, canary_min_requests=3,
            canary_max_error_rate=0.0, canary_max_p99_ratio=50.0,
            shadow_frac=0.0, phase_timeout_s=60.0,
            # the continuous-deploy pipeline under test
            watch=True, watch_interval_s=0.1, gate_dir=None,
            gate_min_agreement=0.8, min_replicas=0, max_replicas=0)
        plane, server = build_server(args)
        server.start_background()
        base = f"http://{server.host}:{server.port}"
        deploy = server.httpd.deploy
        assert deploy is not None and deploy.watcher is not None
        ckpt = None
        try:
            status, health = _get(base, "/v1/healthz")
            assert status == 200 and health["status"] == "ok", health

            # the client load that must never see an error — through
            # checkpoint publish, gated rollout, refusal, and revert
            lenet_px = np.zeros((32, 32, 1)).tolist()
            errors, served = [], [0]
            stop = threading.Event()

            def hammer():
                while not stop.is_set():
                    try:
                        s, out = _post(base, "/v1/models/lenet5/classify",
                                       {"pixels": lenet_px}, timeout=60)
                        assert s == 200 and out["top"], out
                        served[0] += 1
                    except Exception as e:  # noqa: BLE001 — any failure is a lost request
                        errors.append(repr(e))

            t = threading.Thread(target=hammer, daemon=True)
            t.start()
            while served[0] < 5:
                time.sleep(0.01)

            # -- step 1: publish a real checkpoint mid-load ------------
            # fresh random init under PRNGKey(0) == the weights already
            # serving, so the synthetic accuracy gate sees agreement 1.0
            cfg = get_config("lenet5")
            with tempfile.TemporaryDirectory() as seed_dir:
                _, state = load_state(cfg, seed_dir,
                                      log=lambda *a, **k: None)
            ckpt = Checkpointer(
                os.path.join(workdir, "lenet5", "checkpoints"))
            ckpt.save(1, state)
            ckpt.wait_until_finished()

            def promoted():
                _, h = _get(base, "/v1/deploy/lenet5/history")
                ent = h["entries"]
                if ent and ent[-1]["outcome"] == "promoted":
                    return ent
                return None

            entries = _wait_for("auto-deploy of step 1", promoted)
            _, table = _get(base, "/v1/models")
            assert table["models"]["lenet5"]["active_version"] == 2
            outcomes = [e["outcome"] for e in entries]
            assert outcomes == ["candidate", "gate_passed", "promoted"], \
                outcomes
            gate = [e for e in entries
                    if e["outcome"] == "gate_passed"][0]["gate"]
            assert gate["agreement"] == 1.0, gate

            # -- step 2: a bad checkpoint must be refused --------------
            nan_state = state.replace(params=jax.tree_util.tree_map(
                lambda a: np.asarray(a) * np.nan, state.params))
            ckpt.save(2, nan_state)
            ckpt.wait_until_finished()

            def gate_failed():
                _, st = _get(base, "/v1/stats")
                w = st["deploy"]["watcher"]
                return w if w["gate_failures"] >= 1 else None

            watcher_stats = _wait_for("gate refusal of step 2",
                                      gate_failed)
            assert watcher_stats["deploys"] == 1, watcher_stats
            _, table = _get(base, "/v1/models")
            assert table["models"]["lenet5"]["active_version"] == 2, \
                "gate failure must leave the active version serving"
            _, hist = _get(base, "/v1/deploy/lenet5/history")
            last = hist["entries"][-1]
            assert last["outcome"] == "gate_failed", hist["entries"]
            assert "NaN" in last["gate"]["reason"], last

            # -- one-command revert back to v1's weights ---------------
            status, out = _post(base, "/v1/deploy/lenet5/revert")
            assert status == 200 and out["status"] == "reverted", out
            assert out["from_version"] == 2, out
            _, table = _get(base, "/v1/models")
            assert table["models"]["lenet5"]["active_version"] == 3
            # revert is symmetric: v2 was promoted too, so a second
            # revert swings back to its weights (v4 restores v2)
            status, out = _post(base, "/v1/deploy/lenet5/revert")
            assert status == 200 and out["restores"] == 2, (status, out)
            _, table = _get(base, "/v1/models")
            assert table["models"]["lenet5"]["active_version"] == 4
            # unknown model → 404 through the deploy routes
            try:
                status, _ = _get(base, "/v1/deploy/nope/history")
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 404, status

            stop.set()
            t.join(60)
            assert not errors, \
                f"deploy loop lost {len(errors)}: {errors[:3]}"

            # -- observability: stats block + metrics series -----------
            _, stats = _get(base, "/v1/stats")
            dep = stats["deploy"]
            assert dep["history"]["records"] >= 5, dep["history"]
            assert dep["watcher"]["polls"] > 0, dep["watcher"]
            assert stats["plane"]["reverts"] == 2, stats["plane"]
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=60) as r:
                text = r.read().decode()
            for line in text.splitlines():
                if not line or line.startswith("#"):
                    continue
                m = _PROM_LINE.match(line)
                assert m, f"bad metric line: {line}"
                float(m.group(2))
            for series in ("dvt_deploy_history_records_total",
                           "dvt_deploy_watcher_polls_total",
                           "dvt_deploy_deploys_total 1",
                           "dvt_deploy_gate_failures_total 1",
                           "dvt_serve_reverts_total 2"):
                assert series in text, f"missing {series}"
            print(f"deploy-smoke PASS: checkpoint published mid-load "
                  f"auto-deployed to v2 ({served[0]} client requests, "
                  f"0 errors), NaN checkpoint refused by the gate, "
                  f"revert restored v1's weights as v3; "
                  f"{dep['history']['records']} ledger records, "
                  f"{dep['watcher']['polls']} watcher polls, "
                  f"{len(text.splitlines())} metric lines parsed")
        finally:
            if ckpt is not None:
                ckpt.close()
            deploy.stop()
            server.shutdown()
            plane.stop(drain_deadline=5.0)
    return 0


def main():
    # pin the platform before jax initializes (site config can override
    # the env var alone, so set it at the config level too)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    return smoke()


if __name__ == "__main__":
    sys.exit(main())
