"""`make input-smoke` (runs inside `make serve-smoke`): the staged
train-input pipeline end to end on whatever backend is present —
uint8 batches through a DevicePrefetcher into a donated jitted step
for two epochs (identical losses: donation never exposes a clobbered
buffer), the uint8-vs-float32 wire showing exactly 4x fewer image H2D
bytes, the fused Pallas train-ingest parity gate selecting a path and
matching the XLA jitter chain on the same batch, and a clean close()
— producer thread gone, staging-pool allocation bounded by depth.
Run directly, not under pytest."""

import os
import sys
import threading
import time

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/input_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from deep_vision_tpu.data.pipeline import DevicePrefetcher  # noqa: E402
from deep_vision_tpu.ops import preprocess  # noqa: E402
from deep_vision_tpu.parallel import make_mesh  # noqa: E402

BATCH, SIZE, STEPS, DEPTH = 8, 32, 10, 2


def batches(dtype):
    rng = np.random.default_rng(0)
    for _ in range(STEPS):
        img = rng.integers(0, 256, (BATCH, SIZE, SIZE, 3), dtype=np.uint8)
        lbl = rng.integers(0, 10, (BATCH,), dtype=np.int32)
        if dtype == np.float32:
            img = img.astype(np.float32) / 255.0
        yield {"image": img, "label": lbl}


def main():
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])

    def loss_of(batch):
        x = batch["image"]
        if x.dtype == jnp.uint8:
            x = x.astype(jnp.float32) / 255.0
        return jnp.sum(x * x) + jnp.sum(batch["label"])

    step = jax.jit(loss_of, donate_argnums=(0,))

    # -- donation safety: two identical epochs, identical losses --------
    pf = DevicePrefetcher(mesh, depth=DEPTH)
    per_epoch, epoch_stats = [], []
    for _ in range(2):
        stream = pf.iterate(batches(np.uint8))
        per_epoch.append([float(step(b)) for b in stream])
        epoch_stats.append(stream.stats())
    assert per_epoch[0] == per_epoch[1], \
        f"donated epochs diverged: {per_epoch[0][:3]} vs {per_epoch[1][:3]}"
    u8 = epoch_stats[-1]  # stats are per-epoch; the pool persists
    assert u8["batches"] == STEPS
    # staging allocation is bounded by depth, not epoch length
    assert u8["pool"]["allocated"] <= (DEPTH + 2) * 2, u8["pool"]
    assert u8["pool"]["reused"] > 0, u8["pool"]
    print(f"[input-smoke] u8 wire: {u8['batches']} batches, "
          f"stall {u8['input_stall_frac']:.2f}, "
          f"h2d {u8['h2d_bytes_per_step']} B/step, pool {u8['pool']}")

    # -- wire comparison: uint8 images move exactly 4x fewer bytes ------
    f32 = DevicePrefetcher(mesh, depth=DEPTH)
    for b in f32.iterate(batches(np.float32)):
        jax.block_until_ready(b)
    s32 = f32.stats()
    ratio = (s32["h2d_bytes_by_key"]["image"]
             / u8["h2d_bytes_by_key"]["image"])
    assert ratio == 4.0, f"f32/u8 image H2D ratio {ratio} != 4.0"
    print(f"[input-smoke] image H2D f32/u8 ratio {ratio} (exact)")

    # -- fused train-ingest: gate decides, output matches XLA chain -----
    shape = (BATCH, SIZE, SIZE, 3)
    fused_fn = preprocess.make_imagenet_preprocess(
        use_fused=True, fused_shape=shape, mesh=mesh)
    xla_fn = preprocess.make_imagenet_preprocess()
    img = np.random.default_rng(1).integers(0, 256, shape, dtype=np.uint8)
    rng = jax.random.PRNGKey(7)
    out_f = fused_fn({"image": jnp.asarray(img)}, rng, train=True)["image"]
    out_x = xla_fn({"image": jnp.asarray(img)}, rng, train=True)["image"]
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_x),
                               rtol=1e-4, atol=1e-4)
    print(f"[input-smoke] train ingest: "
          f"{'fused pallas' if fused_fn.fused else 'xla'} "
          f"(parity vs XLA jitter chain OK)")

    # -- close(): producer threads gone, nothing left running ----------
    pf.close()
    f32.close()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if not any(t.name.startswith("dvt-prefetch") for t in
                   threading.enumerate()):
            break
        time.sleep(0.05)
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("dvt-prefetch")]
    assert not leaked, f"producer threads leaked: {leaked}"
    print("[input-smoke] OK")


if __name__ == "__main__":
    main()
