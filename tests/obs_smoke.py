"""`make obs-smoke` (runs inside `make serve-smoke`): boot the real
cli.serve wiring on a random port, then assert the observability
surface end to end — /metrics parses as Prometheus text and its
counters advance between scrapes, a ?debug=1 request echoes a
client-chosen X-DVT-Request-Id and returns a span whose stage
breakdown accounts for its whole measured total, /v1/traces serves the
ring — and finally the same through a real gateway hop
(cli.gateway.build_gateway): the id must cross the wire into the
BACKEND's trace ring and the gateway's own /metrics must parse.
Run directly, not under pytest."""

import argparse
import json
import os
import re
import sys
import tempfile
import time
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/obs_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SAMPLE_RE = re.compile(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)")


def parse_metrics(text: str) -> dict:
    """Validate every exposition line; return {name: {labels_str: value}}."""
    samples: dict = {}
    for line in text.splitlines():
        assert line.strip() == line and line, f"bad line {line!r}"
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert not line.startswith("#"), f"unknown comment {line!r}"
        m = _SAMPLE_RE.fullmatch(line)
        assert m, f"unparseable sample {line!r}"
        name, labels, value = m.groups()
        v = float("inf") if value == "+Inf" else float(value)
        samples.setdefault(name, {})[labels or ""] = v
    return samples


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        blob = r.read()
        return r.status, dict(r.headers), blob


def _classify(base, rid=None, debug=False):
    body = json.dumps({"pixels": np.zeros((32, 32, 1)).tolist()}).encode()
    headers = {"Content-Type": "application/json"}
    if rid:
        headers["X-DVT-Request-Id"] = rid
    url = base + "/v1/classify" + ("?debug=1" if debug else "")
    req = urllib.request.Request(url, data=body, headers=headers)
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, dict(r.headers), json.loads(r.read())


def main():
    from deep_vision_tpu.cli.gateway import build_gateway
    from deep_vision_tpu.cli.serve import build_server

    with tempfile.TemporaryDirectory() as workdir:
        args = argparse.Namespace(
            model="lenet5", workdir=workdir, stablehlo=None,
            host="127.0.0.1", port=0, max_batch=4, max_wait_ms=2.0,
            buckets=None, max_queue=64, warmup=False, verbose=False,
            pipeline_depth=2, faults="", fault_seed=0,
            serve_devices=1, shard_batches=False,
            wire_dtype="float32", infer_dtype="float32")
        engine, server = build_server(args)
        server.start_background()
        base = f"http://{server.host}:{server.port}"
        gw = gsrv = None
        try:
            # -- span + request id on the backend itself --
            rid = "0bs5m0ke00000001"
            status, headers, payload = _classify(base, rid=rid, debug=True)
            assert status == 200, status
            assert headers["X-DVT-Request-Id"] == rid, headers
            trace = payload["trace"]
            assert trace["request_id"] == rid, trace
            covered = sum(trace["stages"].values())
            assert covered >= 0.95 * trace["total_ms"], trace
            # -- /metrics parses and advances between scrapes --
            status, headers, blob = _get(base, "/metrics")
            assert status == 200, status
            assert headers["Content-Type"].startswith("text/plain"), headers
            first = parse_metrics(blob.decode())
            lab = '{model="lenet5"}'
            assert first["dvt_serve_up"][lab] == 1, first["dvt_serve_up"]
            _classify(base)
            # the handler seals its span AFTER replying, so give the
            # trace counter a moment to land before comparing scrapes
            monotone = ("dvt_serve_requests_served_total",
                        "dvt_serve_traces_finished_total",
                        "dvt_serve_compute_seconds_total")
            deadline = time.monotonic() + 5.0
            while True:
                second = parse_metrics(_get(base, "/metrics")[2].decode())
                if all(second[n][lab] > first[n][lab] for n in monotone) \
                        or time.monotonic() > deadline:
                    break
                time.sleep(0.01)
            for name in monotone:
                assert second[name][lab] > first[name][lab], name
            mfu = second["dvt_serve_mfu"][lab]
            assert 0 < mfu < 1, mfu
            # -- the trace ring is served --
            traces = json.loads(_get(base, "/v1/traces?n=8")[2])
            assert any(t["request_id"] == rid for t in traces["traces"]), \
                [t["request_id"] for t in traces["traces"]]
            # -- and the same through a real gateway hop --
            gw, gsrv = build_gateway(argparse.Namespace(
                backend=[f"{server.host}:{server.port}"],
                host="127.0.0.1", port=0, probe_interval_ms=50.0))
            gsrv.start_background()
            gbase = f"http://{gsrv.host}:{gsrv.port}"
            grid = "0bs5m0ke00000002"
            status, headers, payload = _classify(gbase, rid=grid,
                                                 debug=True)
            assert status == 200, status
            assert headers["X-DVT-Request-Id"] == grid, headers
            assert payload["trace"]["request_id"] == grid, payload
            assert payload["gateway_trace"]["request_id"] == grid, payload
            assert "backend_hop" in payload["gateway_trace"]["stages"]
            # the id crossed the wire: the BACKEND's ring holds it
            assert any(t["request_id"] == grid
                       for t in engine.tracer.recent(32))
            gsamples = parse_metrics(_get(gbase, "/metrics")[2].decode())
            assert gsamples["dvt_gateway_proxied_total"][""] >= 1
            assert gsamples["dvt_gateway_routable_backends"][""] == 1
            print(f"obs-smoke PASS: request id {rid} echoed with "
                  f"{covered:.3f}/{trace['total_ms']:.3f} ms accounted "
                  f"({covered / max(trace['total_ms'], 1e-9):.1%}), "
                  f"serve+gateway /metrics parsed "
                  f"({len(second)}+{len(gsamples)} series), "
                  f"serving_mfu {mfu:.3g}, id {grid} propagated "
                  f"gateway -> backend ring")
        finally:
            if gsrv is not None:
                gsrv.shutdown()
            if gw is not None:
                gw.stop()
            server.shutdown()
            engine.stop(drain_deadline=5.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
