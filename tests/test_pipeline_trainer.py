"""Pipeline parallelism as a TRAINING MODE (parallel/pipelined.py):
``cli.train -m hourglass* --mesh data=d,pipe=p`` trains the real stacked
hourglass through the unified Trainer, and the numbers match the
monolithic :class:`StackedHourglass` — forward exactly, and full
``fit()`` trajectories within f32 tolerance (VERDICT r3 #1)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.core.config import OptimizerConfig, TrainConfig
from deep_vision_tpu.core.trainer import Trainer
from deep_vision_tpu.data.pose import PoseLoader, synthetic_pose_dataset
from deep_vision_tpu.models.hourglass import (
    StackedHourglass,
    merge_stacked_variables,
)
from deep_vision_tpu.parallel import make_mesh
from deep_vision_tpu.parallel.pipeline import unstack_stages
from deep_vision_tpu.parallel.pipelined import PipelinedModel
from deep_vision_tpu.tasks.pose import PoseTask

HEAT = 3


def _toy_model():
    return StackedHourglass(num_stack=4, num_heatmap=HEAT, filters=8,
                            order=1, dtype=jnp.float32)


def _toy_cfg(name, **kw):
    # SGD, not adam: the trajectory-match tests compare two compiled
    # programs of the SAME math, whose true-zero-gradient directions
    # (conv biases feeding BN — the batch-mean subtraction cancels them)
    # carry ~1e-10 float noise.  SGD keeps that noise at 1e-10; adam's
    # g/sqrt(g²) normalization turns each program's noise SIGN into a
    # full ±lr step, so degenerate params diverge while losses agree.
    cfg = TrainConfig(
        name=name, model=_toy_model, task="pose", batch_size=8,
        total_epochs=2, optimizer=OptimizerConfig(name="sgd",
                                                  learning_rate=1e-3),
        image_size=32, num_classes=HEAT, half_precision=False,
        log_every_steps=1)
    return dataclasses.replace(cfg, **kw) if kw else cfg


def _loader(n=16, batch=8, seed=0):
    samples = synthetic_pose_dataset(n, 32, HEAT, seed=seed)
    return PoseLoader(samples, batch, 32, 8, HEAT, train=True, seed=7)


def _stage_list(variables):
    """Pipelined variables → per-stage [{'params', 'batch_stats'}]."""
    out = []
    for p, s in zip(unstack_stages(variables["params"]["stages"]),
                    unstack_stages(variables["batch_stats"]["stages"])):
        out.append({"params": p, "batch_stats": s})
    return out


@pytest.mark.slow
def test_layout_remap_roundtrip_and_sequential_forward():
    """The monolithic↔pipelined variable remap is a pure rename: the
    stem + per-stage HourglassStack sequence (eager, no pipeline) emits
    bit-identical heatmaps from remapped monolithic params, and the
    roundtrip is identity."""
    from deep_vision_tpu.models.hourglass import HourglassStack, HourglassStem

    mono = _toy_model()
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
    mv = mono.init({"params": jax.random.PRNGKey(1)}, x[:1], train=False)

    mesh = make_mesh({"data": 1, "pipe": 4})
    pm = PipelinedModel.from_stacked_hourglass(mono, mesh)
    pv = pm.init({"params": jax.random.PRNGKey(2)}, x[:1], train=False)
    conv = pm.import_monolithic_variables(mv, pv)

    out_m = mono.apply(mv, x, train=False)
    stem = HourglassStem(filters=8, dtype=jnp.float32)
    stage = HourglassStack(num_heatmap=HEAT, filters=8, order=1,
                           dtype=jnp.float32)
    carry = stem.apply({"params": conv["params"]["stem"],
                        "batch_stats": conv["batch_stats"]["stem"]},
                       x, train=False)
    for s, sv in enumerate(_stage_list(conv)):
        carry, heat = stage.apply(sv, carry, train=False)
        np.testing.assert_array_equal(np.asarray(out_m[s]),
                                      np.asarray(heat))

    back = merge_stacked_variables(
        {"params": conv["params"]["stem"],
         "batch_stats": conv["batch_stats"]["stem"]},
        _stage_list(conv))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), dict(mv["params"]),
        back["params"])


@pytest.mark.slow
def test_pipelined_forward_matches_monolithic_exactly():
    """Same params (remapped) → bit-equal heatmaps from the pipelined
    wrapper and the monolithic network, plus an exact layout roundtrip."""
    mono = _toy_model()
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 32, 3))
    mv = mono.init({"params": jax.random.PRNGKey(1)}, x[:1], train=False)

    mesh = make_mesh({"data": 1, "pipe": 4})
    pm = PipelinedModel.from_stacked_hourglass(mono, mesh,
                                               num_microbatches=1)
    pv = pm.init({"params": jax.random.PRNGKey(2)}, x[:1], train=False)
    conv = pm.import_monolithic_variables(mv, pv)

    out_m = mono.apply(mv, x, train=False)
    out_p = pm.apply(conv, x, train=False)
    assert len(out_m) == len(out_p) == 4
    for a, b in zip(out_m, out_p):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # layout roundtrip: monolithic -> pipelined -> monolithic is identity
    back = merge_stacked_variables(
        {"params": conv["params"]["stem"],
         "batch_stats": conv["batch_stats"]["stem"]},
        _stage_list(conv))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), dict(mv["params"]),
        back["params"])


@pytest.mark.slow
def test_pipelined_fit_matches_monolithic_trajectory(tmp_path):
    """Trainer.fit on a {data:1, pipe:4} mesh with 1 microbatch (full-
    batch BN — identical semantics) reproduces the monolithic
    StackedHourglass trajectory: same per-step losses, same final params
    within f32 tolerance."""
    cfg_a = _toy_cfg("hg_mono")
    cfg_b = _toy_cfg("hg_pipe")
    mesh1 = make_mesh({"data": 1}, devices=jax.devices()[:1])
    meshp = make_mesh({"data": 1, "pipe": 4})

    trainer_a = Trainer(cfg_a, _toy_model(), PoseTask(), mesh=mesh1,
                        workdir=str(tmp_path / "mono"))
    pm = PipelinedModel.from_stacked_hourglass(_toy_model(), meshp,
                                               num_microbatches=1)
    trainer_b = Trainer(cfg_b, pm, PoseTask(), mesh=meshp,
                        workdir=str(tmp_path / "pipe"))

    sample = next(iter(_loader()))
    state_a = trainer_a.init_state(sample)
    state_b = trainer_b.init_state(sample)
    conv = pm.import_monolithic_variables(
        {"params": jax.device_get(state_a.params),
         "batch_stats": jax.device_get(state_a.batch_stats)},
        {"params": jax.device_get(state_b.params),
         "batch_stats": jax.device_get(state_b.batch_stats)})
    state_b = trainer_b._place_state(state_b.replace(
        params=conv["params"], batch_stats=conv["batch_stats"],
        opt_state=trainer_b.tx.init(conv["params"])))

    state_a = trainer_a.fit(_loader(), state=state_a)
    state_b = trainer_b.fit(_loader(), state=state_b)

    # per-step train losses agree (logged every step)
    hist_a = trainer_a.logger.state_dict()["train_loss"]["values"]
    hist_b = trainer_b.logger.state_dict()["train_loss"]["values"]
    assert len(hist_a) == len(hist_b) > 0
    np.testing.assert_allclose(hist_a, hist_b, rtol=1e-4)

    # final params agree after export back to the monolithic layout.
    # Tolerance note: the strict trajectory evidence is the per-step loss
    # match above (rtol 1e-4; measured agreement ~1e-6 at step 1 growing
    # to ~2e-5 by step 4).  Training through batch-mode BN is chaotic in
    # f32 — two differently-fused XLA programs of the SAME math amplify
    # ~1e-7 per-step rounding into ~2e-3 absolute param drift by step 4
    # (measured; grows with the 2e3 loss scale) — so the param check is a
    # sanity band, not bit-parity.
    merged = pm.export_monolithic_variables(state_b.params,
                                            state_b.batch_stats)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-2, atol=5e-3),
        dict(jax.device_get(state_a.params)), merged["params"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-2, atol=5e-3),
        dict(jax.device_get(state_a.batch_stats)), merged["batch_stats"])


@pytest.mark.slow
def test_pipelined_fit_data_pipe_mesh_exact_vs_pipe1(tmp_path):
    """The production mesh {data:2, pipe:4} with real microbatching is
    EXACTLY the pipe=1 sequential run with the same microbatch-BN
    semantics — the pipeline mechanism itself adds no numerics — and the
    loss falls."""
    mesh_p4 = make_mesh({"data": 2, "pipe": 4})
    mesh_p1 = make_mesh({"data": 2, "pipe": 1},
                        devices=jax.devices()[:2])

    losses = {}
    finals = {}
    for tag, mesh in (("p4", mesh_p4), ("p1", mesh_p1)):
        pm = PipelinedModel.from_stacked_hourglass(
            _toy_model(), mesh, num_microbatches=2)
        trainer = Trainer(_toy_cfg(f"hg_{tag}"), pm, PoseTask(), mesh=mesh,
                          workdir=str(tmp_path / tag))
        state = trainer.fit(_loader())
        losses[tag] = trainer.logger.state_dict()["train_loss"]["values"]
        finals[tag] = pm.export_monolithic_variables(state.params,
                                                     state.batch_stats)
    np.testing.assert_allclose(losses["p4"], losses["p1"], rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                np.asarray(b),
                                                rtol=1e-4, atol=1e-5),
        finals["p4"]["params"], finals["p1"]["params"])
    assert losses["p4"][-1] < losses["p4"][0]


@pytest.mark.slow
def test_cli_pose_pipeline_smoke(tmp_path):
    """The full CLI path: cli.train -m hourglass_toy --mesh data=2,pipe=4
    runs fit + eval end to end through the pipelined model — and the
    resulting checkpoint SERVES through cli.infer's loader, which detects
    the pipeline layout and converts it to the monolithic network."""
    from deep_vision_tpu.cli import train as cli_train
    from deep_vision_tpu.cli.infer import _load_state
    from deep_vision_tpu.core.config import get_config

    workdir = tmp_path / "cli"
    rc = cli_train.main([
        "-m", "hourglass_toy", "--synthetic", "--synthetic-size", "16",
        "--epochs", "1", "--batch-size", "8", "--image-size", "32",
        "--mesh", "data=2,pipe=4", "--microbatches", "2",
        "--workdir", str(workdir)])
    assert rc == 0

    cfg = get_config("hourglass_toy")
    cfg.image_size = 32
    model, state = _load_state(cfg, str(workdir))
    # monolithic layout (flax auto-names, no stem/stages nesting) and a
    # working forward at serving shape
    assert "stem" not in state.params and "Conv_0" in state.params
    out = model.apply(
        {"params": state.params, "batch_stats": state.batch_stats},
        jnp.zeros((1, 32, 32, 3)), train=False)
    assert len(out) == 4 and out[0].shape == (1, 8, 8, 8)
    # the restored weights are trained, not the template init
    assert float(jnp.abs(out[-1]).max()) > 0


@pytest.mark.slow
def test_centernet_pipelined_forward_and_train_step(tmp_path):
    """The OTHER stacked family: CenterNet through the same pipeline
    mode — remapped monolithic params give bit-equal 3-head outputs, the
    layout roundtrip is identity, and a real Trainer.train_step on a
    {data:2, pipe:2} mesh matches the pipe=1 run exactly and learns."""
    from deep_vision_tpu.data.detection import (
        CenterNetLoader,
        synthetic_detection_dataset,
    )
    from deep_vision_tpu.models.centernet import CenterNet
    from deep_vision_tpu.tasks.centernet import CenterNetTask

    mono = CenterNet(num_classes=3, num_stack=2, order=2,
                     filters=(16, 16, 24), dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 32, 3))
    mv = mono.init({"params": jax.random.PRNGKey(1)}, x[:1], train=False)

    mesh2 = make_mesh({"data": 1, "pipe": 2})
    pm = PipelinedModel.for_model(mono, mesh2, num_microbatches=1)
    pv = pm.init({"params": jax.random.PRNGKey(2)}, x[:1], train=False)
    conv = pm.import_monolithic_variables(mv, pv)
    out_m = mono.apply(mv, x, train=False)
    out_p = pm.apply(conv, x, train=False)
    for heads_m, heads_p in zip(out_m, out_p):
        for a, b in zip(heads_m, heads_p):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    back = pm.export_monolithic_variables(conv["params"],
                                          conv["batch_stats"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), dict(mv["params"]),
        back["params"])

    # real training step through the Trainer on two meshes, same init
    samples = synthetic_detection_dataset(8, 32, 3, seed=5)
    loader = CenterNetLoader(samples, 8, 3, 32, train=True, augment=False,
                             seed=0)
    batch = next(iter(loader))
    losses = {}
    for tag, sizes in (("p2", {"data": 2, "pipe": 2}),
                       ("p1", {"data": 2, "pipe": 1})):
        mesh = make_mesh(sizes, devices=jax.devices()[:2 * sizes["pipe"]])
        pmod = PipelinedModel.for_model(mono, mesh, num_microbatches=2)
        cfg = _toy_cfg(f"cn_{tag}")
        trainer = Trainer(cfg, pmod, CenterNetTask(3), mesh=mesh,
                          workdir=str(tmp_path / tag))
        state = trainer.init_state(batch)
        ls = []
        for _ in range(2):
            state, metrics = trainer.train_step(state, dict(batch))
            ls.append(float(jax.device_get(metrics["loss"])))
        losses[tag] = ls
    assert all(np.isfinite(losses["p2"])), losses
    np.testing.assert_allclose(losses["p2"], losses["p1"], rtol=1e-5)
    assert losses["p2"][1] < losses["p2"][0], losses


@pytest.mark.slow
def test_pipelined_composes_with_ema_and_grad_accum(tmp_path):
    """The docstring's composition claim, exercised: EMA + grad-accum
    ride the SAME Trainer step with the pipelined model — losses finite
    and falling, the EMA copy tracks sharded stage params, and the
    grad-accum step stays exact vs the monolithic accumulation (mean
    losses, BN threading through microbatches then pipeline state)."""
    meshp = make_mesh({"data": 2, "pipe": 4})
    pm = PipelinedModel.for_model(_toy_model(), meshp, num_microbatches=2)
    cfg = _toy_cfg("hg_recipe", ema_decay=0.5, grad_accum_steps=2)
    trainer = Trainer(cfg, pm, PoseTask(), mesh=meshp,
                      workdir=str(tmp_path / "recipe"))
    batch = next(iter(_loader()))
    state = trainer.init_state(batch)
    losses = []
    for _ in range(3):
        state, metrics = trainer.train_step(state, dict(batch))
        losses.append(float(jax.device_get(metrics["loss"])))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # EMA present, stage-stacked, and moved off the init params
    ema_leaf = jax.tree_util.tree_leaves(state.ema_params["stages"])[0]
    assert ema_leaf.shape[0] == 4  # stage axis preserved
    diffs = jax.tree_util.tree_map(
        lambda e, p: float(np.abs(np.asarray(e) - np.asarray(p)).max()),
        jax.device_get(state.ema_params), jax.device_get(state.params))
    assert max(jax.tree_util.tree_leaves(diffs)) > 0  # averaging, not copy
