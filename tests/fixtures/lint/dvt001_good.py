"""DVT001 negative fixture: every guarded write holds the lock (directly,
via the *_locked convention, via holds=, or via an explicit disable)."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.resets = 0  # guarded-by: _lock
        self.free = 0  # unguarded on purpose: single-writer thread

    def bump(self):
        with self._lock:
            self.hits += 1
            self._miss_locked()

    def _miss_locked(self):
        self.misses += 1  # ok: *_locked suffix means caller holds the lock

    def reset(self):  # dvtlint: holds=_lock
        self.resets += 1  # ok: annotated as called-with-lock-held

    def racy_but_audited(self):
        self.hits = 0  # dvtlint: disable=DVT001 — test-only reset, single-threaded

    def single_writer(self):
        self.free += 1  # ok: never declared guarded
