"""DVT003 negative fixture: the whitelisted bulk fetch, host-derived
values, and identical code outside any hot function."""
import jax
import numpy as np


class Engine:
    def drain(self, out):  # dvtlint: hot
        host = jax.device_get(out)  # dvtlint: disable=DVT003 — the single bulk D2H
        rows = [np.asarray(host)[i] for i in range(2)]  # ok: host memory already
        total = float(host.sum())  # ok: host-derived statement
        return rows, total

    def offline_report(self, out):  # not hot: same calls are fine here
        return float(np.asarray(jax.device_get(out)).mean())
