"""DVT007 bad: blocking primitives with no timeout — each one pins its
thread forever the moment the peer stalls."""

import queue
import socket
import threading
from http.client import HTTPConnection


def drain(q: "queue.Queue"):
    return q.get()  # blocking queue get, no timeout


def supervise(worker: threading.Thread, done: threading.Event):
    done.wait()  # event wait, no timeout
    worker.join()  # thread join, no timeout


def dial(host, port):
    conn = HTTPConnection(host, port)  # no connect timeout
    sock = socket.create_connection((host, port))  # no connect timeout
    return conn, sock
