"""DVT005 negative fixture: monotonic intervals; wall clock only as a
pass-through record timestamp."""
import time


def elapsed(work):
    t0 = time.monotonic()
    work()
    return time.monotonic() - t0


def log_record(name):
    return {"ts": round(time.time(), 6), "event": name}  # ok: timestamp field
