"""DVT004 negative fixture: pure traced code (explicit PRNG keys are
fine), and side effects in plain host functions."""
import time

import jax
import jax.numpy as jnp


def make_step():
    def step(x, key):
        noise = jax.random.normal(key, x.shape)  # ok: explicit PRNG key
        return jnp.tanh(x + noise)

    return jax.jit(step)


def host_timer():  # never traced: wall work is fine
    t0 = time.monotonic()
    print("host side")
    return time.monotonic() - t0
