"""DVT005 positive fixture: intervals computed from the wall clock."""
import time


def elapsed(work):
    t0 = time.time()
    work()
    return time.time() - t0  # BAD: NTP can step this negative


class Meter:
    def __init__(self):
        self.start = time.time()

    def age(self):
        return time.time() - self.start  # BAD: wall-clock interval
