"""DVT006 positive fixture: broad excepts without (full) justification."""


def swallow(fn):
    try:
        return fn()
    except Exception:
        return None  # BAD: no justification at all


def swallow_bare(fn):
    try:
        return fn()
    except:
        return None  # BAD: bare except


def swallow_reasonless(fn):
    try:
        return fn()
    except Exception:  # noqa: BLE001
        return None  # BAD: noqa without the required reason
