"""DVT007 good: every blocking primitive carries a timeout (or is
provably non-blocking); the one deliberate forever-block is
escape-hatched with its reason."""

import queue
import socket
import threading
from http.client import HTTPConnection


def drain(q: "queue.Queue"):
    return q.get(timeout=1.0)


def drain_nonblocking(q: "queue.Queue"):
    return q.get_nowait()


def lookup(cfg: dict):
    # dict.get takes a key — positional args mean "not a blocking get"
    return cfg.get("key")


def supervise(worker: threading.Thread, done: threading.Event):
    if done.wait(timeout=5.0):
        worker.join(timeout=5.0)


def dial(host, port):
    conn = HTTPConnection(host, port, timeout=10.0)
    sock = socket.create_connection((host, port), timeout=10.0)
    return conn, sock


def reap(worker: threading.Thread):
    # process shutdown: waiting forever for the worker IS the contract
    worker.join()  # dvtlint: disable=DVT007
