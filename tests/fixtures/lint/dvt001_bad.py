"""DVT001 positive fixture: guarded attribute written outside the lock."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.table = {}  # guarded-by: _lock

    def bump(self):
        self.hits += 1  # BAD: guarded write with no lock held

    def store(self, k, v):
        self.table[k] = v  # BAD: subscript store on a guarded attr

    def ok(self):
        with self._lock:
            self.misses += 1
