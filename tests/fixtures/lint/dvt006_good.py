"""DVT006 negative fixture: narrow excepts, or justified broad ones."""


def narrow(fn):
    try:
        return fn()
    except (ValueError, KeyError):
        return None


def justified(fn):
    try:
        return fn()
    except Exception:  # noqa: BLE001 — plugin code may raise anything; fall back to default
        return None
