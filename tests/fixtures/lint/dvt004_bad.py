"""DVT004 positive fixture: side effects inside jit-traced functions."""
import functools
import time

import jax
import numpy as np


def make_step():
    def step(x):
        t = time.time()  # BAD: trace-time constant, not a clock
        np.random.seed(0)  # BAD: host randomness vanishes from the trace
        print("tracing", x)  # BAD: I/O fires at trace time only
        return x * t

    return jax.jit(step)


class Holder:
    count = 0


@functools.partial(jax.jit, static_argnames=())
def bump(x):
    Holder.count = 1  # BAD: Python mutation baked into (or lost from) trace
    return x
