"""DVT002 positive fixture: two lock-order cycles — one through
cross-class call edges, one through annotated nested withs."""
import threading

x_lock = threading.Lock()
y_lock = threading.Lock()


class A:
    def __init__(self):
        self._lock = threading.Lock()
        self.peer = B()

    def one(self):
        with self._lock:
            self.peer.poke()  # acquires B._lock while A._lock held

    def nab(self):
        with self._lock:
            pass


class B:
    def __init__(self):
        self._lock = threading.Lock()
        self.peer = A()

    def poke(self):
        with self._lock:
            pass

    def two(self):
        with self._lock:
            self.peer.nab()  # acquires A._lock while B._lock held -> cycle


def left():
    with x_lock:  # dvtlint: lock=fix.X.lock
        with y_lock:  # dvtlint: lock=fix.Y.lock
            pass


def right():
    with y_lock:  # dvtlint: lock=fix.Y.lock
        with x_lock:  # dvtlint: lock=fix.X.lock -> cycle with left()
            pass
