"""DVT003 positive fixture: device->host syncs inside a hot function."""
import jax
import numpy as np


class Engine:
    def step(self, out):  # dvtlint: hot
        fetched = jax.device_get(out)  # BAD: device_get always flags
        out.block_until_ready()  # BAD: explicit sync barrier
        return fetched

    def score(self, dev):  # dvtlint: hot
        a = np.asarray(dev)  # BAD: silently copies device -> host
        b = dev.item()  # BAD: scalar sync
        return float(dev) + a + b  # BAD: float() on a device value
