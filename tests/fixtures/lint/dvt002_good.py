"""DVT002 negative fixture: nesting exists, but every path agrees on the
order (A before B, X before Y) — a DAG, not a cycle."""
import threading

x_lock = threading.Lock()
y_lock = threading.Lock()


class A:
    def __init__(self):
        self._lock = threading.Lock()
        self.peer = B()

    def one(self):
        with self._lock:
            self.peer.poke()

    def other(self):
        with self._lock:
            self.peer.poke()  # same direction: still A -> B


class B:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            pass


def left():
    with x_lock:  # dvtlint: lock=fix.X.lock
        with y_lock:  # dvtlint: lock=fix.Y.lock
            pass


def also_left():
    with x_lock:  # dvtlint: lock=fix.X.lock
        with y_lock:  # dvtlint: lock=fix.Y.lock
            pass
