"""Params EMA (config.ema_decay): the modern-recipe averaged copy —
updated inside the jitted step, scored by eval, checkpointed with the
state."""

import jax
import numpy as np
import pytest

from deep_vision_tpu.core.config import get_config
from deep_vision_tpu.core.trainer import Trainer
from deep_vision_tpu.data.loader import ArrayLoader
from deep_vision_tpu.data.mnist import synthetic_mnist
from deep_vision_tpu.tasks.classification import ClassificationTask


def _trainer(tmp_path, mesh, decay):
    cfg = get_config("lenet5")
    cfg.total_epochs = 1
    cfg.batch_size = 32
    cfg.ema_decay = decay
    return Trainer(cfg, cfg.model(), ClassificationTask(10),
                   mesh=mesh, workdir=str(tmp_path))


@pytest.mark.slow
def test_ema_tracks_param_trajectory(tmp_path, mesh1):
    """After k steps, ema == d_t·ema + (1−d_t)·params applied per step to
    the actual param trajectory, with the warmup schedule
    d_t = min(d, (1+t)/(10+t)) (verified against a host-side replay)."""
    d = 0.5
    trainer = _trainer(tmp_path, mesh1, d)
    data = synthetic_mnist(96)
    loader = ArrayLoader(data, 32, shuffle=False)
    batches = list(loader)
    state = trainer.init_state(batches[0])

    expected = jax.tree_util.tree_map(np.asarray,
                                      jax.device_get(state.params))
    for b in batches:
        state, _ = trainer.train_step(state, dict(b))
        t = float(jax.device_get(state.step))
        d_t = min(d, (1.0 + t) / (10.0 + t))
        p = jax.tree_util.tree_map(np.asarray, jax.device_get(state.params))
        expected = jax.tree_util.tree_map(
            lambda e, q: d_t * e + (1 - d_t) * q, expected, p)

    jax.tree_util.tree_map(
        lambda e, a: np.testing.assert_allclose(
            e, np.asarray(a), rtol=1e-5, atol=1e-6),
        expected, jax.device_get(state.ema_params))


def test_eval_scores_the_ema_copy(tmp_path, mesh1):
    """With EMA on, evaluate() must use ema_params: zeroed EMA weights ⇒
    uniform logits ⇒ loss exactly ln(10), regardless of how good the raw
    params are."""
    trainer = _trainer(tmp_path, mesh1, 0.9)
    data = synthetic_mnist(64)
    loader = ArrayLoader(data, 32, shuffle=False)
    state = trainer.init_state(next(iter(loader)))
    state = state.replace(ema_params=jax.tree_util.tree_map(
        np.zeros_like, jax.device_get(state.ema_params)))
    m = trainer.evaluate(state, loader)
    np.testing.assert_allclose(m["loss"], np.log(10.0), atol=1e-3)


def test_ema_off_keeps_empty_tree(tmp_path, mesh1):
    trainer = _trainer(tmp_path, mesh1, 0.0)
    data = synthetic_mnist(32)
    state = trainer.init_state(next(iter(ArrayLoader(data, 32))))
    assert jax.tree_util.tree_leaves(state.ema_params) == []


def test_ema_decay_out_of_range_rejected(tmp_path, mesh1):
    with pytest.raises(ValueError, match="ema_decay"):
        _trainer(tmp_path, mesh1, 1.0)


@pytest.mark.slow
def test_resume_enabling_ema_seeds_from_restored_params(tmp_path, mesh1):
    """Turning --ema-decay on over a checkpoint trained WITHOUT EMA must
    seed the EMA from the restored (trained) params — not crash on the
    missing subtree, not keep the fresh random init."""
    data = synthetic_mnist(64)
    loader = ArrayLoader(data, 32, seed=0)

    t0 = _trainer(tmp_path, mesh1, 0.0)
    s0 = t0.fit(loader)

    t1 = _trainer(tmp_path, mesh1, 0.5)
    s1 = t1.maybe_resume(t1.init_state(next(iter(loader))))
    assert int(jax.device_get(s1.step)) == int(jax.device_get(s0.step))
    jax.tree_util.tree_map(
        lambda e, p: np.testing.assert_array_equal(np.asarray(e),
                                                   np.asarray(p)),
        jax.device_get(s1.ema_params), jax.device_get(s1.params))
    s1, m = t1.train_step(s1, dict(next(iter(loader))))  # no crash
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow
def test_infer_load_state_serves_ema_weights(tmp_path, mesh1):
    """cli.infer's loader must hand every subcommand the averaged copy
    when the checkpoint carries one."""
    from deep_vision_tpu.cli.infer import _load_state

    data = synthetic_mnist(64)
    loader = ArrayLoader(data, 32, seed=0)
    trainer = _trainer(tmp_path, mesh1, 0.9)
    final = trainer.fit(loader)

    _, served = _load_state(trainer.config, str(tmp_path))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        jax.device_get(served.params), jax.device_get(final.ema_params))
    # and it really is the EMA, not the raw weights
    raw, ema = jax.device_get((final.params, final.ema_params))
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(a - b).max()), raw, ema)
    assert max(jax.tree_util.tree_leaves(diffs)) > 0
