"""Persistent-compile-cache plumbing (core/compile_cache.py)."""

import jax

from deep_vision_tpu.core.compile_cache import enable_compile_cache


def test_enable_sets_jax_config(tmp_path):
    p = enable_compile_cache(str(tmp_path / "xla"))
    assert p == str(tmp_path / "xla")
    assert jax.config.jax_compilation_cache_dir == p


def test_env_opt_out(tmp_path, monkeypatch):
    monkeypatch.setenv("DEEP_VISION_TPU_NO_COMPILE_CACHE", "1")
    assert enable_compile_cache(str(tmp_path / "xla2")) is None
