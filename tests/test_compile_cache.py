"""Persistent-compile-cache plumbing (core/compile_cache.py)."""

import jax
import pytest

from deep_vision_tpu.core.compile_cache import enable_compile_cache


@pytest.fixture
def restore_cache_config():
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    yield
    jax.config.update("jax_compilation_cache_dir", prev_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)


def test_enable_sets_jax_config(tmp_path, restore_cache_config):
    p = enable_compile_cache(str(tmp_path / "xla"))
    assert p == str(tmp_path / "xla")
    assert jax.config.jax_compilation_cache_dir == p


def test_env_opt_out(tmp_path, monkeypatch):
    monkeypatch.setenv("DEEP_VISION_TPU_NO_COMPILE_CACHE", "1")
    assert enable_compile_cache(str(tmp_path / "xla2")) is None
