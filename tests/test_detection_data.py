"""Detection data pipeline + dvrec record format tests."""

import numpy as np
import pytest

from deep_vision_tpu.data.detection import (
    DetectionLoader,
    flip_boxes_lr,
    random_crop_with_boxes,
    synthetic_detection_dataset,
)
from deep_vision_tpu.data.records import (
    load_detection_records,
    read_records,
    write_detection_records,
)


def test_flip_boxes():
    b = np.array([[0.1, 0.2, 0.4, 0.6]], np.float32)
    f = flip_boxes_lr(b)
    np.testing.assert_allclose(f, [[0.6, 0.2, 0.9, 0.6]], atol=1e-6)
    np.testing.assert_allclose(flip_boxes_lr(f), b, atol=1e-6)


def test_random_crop_keeps_centers():
    rng = np.random.default_rng(0)
    img = np.zeros((100, 100, 3), np.uint8)
    boxes = np.array([[0.4, 0.4, 0.6, 0.6]], np.float32)
    for _ in range(10):
        crop, new_boxes, keep = random_crop_with_boxes(img, boxes, rng)
        assert keep.sum() >= 1
        assert (new_boxes >= 0).all() and (new_boxes <= 1).all()


def test_loader_static_shapes():
    samples = synthetic_detection_dataset(8, image_size=64, num_classes=3)
    loader = DetectionLoader(samples, batch_size=4, num_classes=3,
                             image_size=64)
    batch = next(iter(loader))
    assert batch["image"].shape == (4, 64, 64, 3)
    assert batch["y_true_0"].shape == (4, 8, 8, 3, 8)
    assert batch["y_true_2"].shape == (4, 2, 2, 3, 8)
    assert batch["boxes"].shape == (4, 100, 4)
    assert batch["boxes_mask"].sum() >= 4  # ≥1 box per image


def test_records_roundtrip(tmp_path):
    samples = synthetic_detection_dataset(6, image_size=48, num_classes=2)
    write_detection_records(samples, str(tmp_path), "train", num_shards=2,
                            num_workers=1)
    loaded = load_detection_records(str(tmp_path), "train")
    assert len(loaded) == 6
    # boxes/classes survive exactly; images survive JPEG (lossy) decode
    orig_boxes = sorted(tuple(np.round(b, 5)) for s in samples
                        for b in s["boxes"])
    got_boxes = sorted(tuple(np.round(b, 5)) for s in loaded
                       for b in s["boxes"])
    assert orig_boxes == got_boxes
    img = loaded[0]["image"]
    assert img.shape == (48, 48, 3) and img.dtype == np.uint8


def test_records_reject_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_detection_records(str(tmp_path), "val")


def test_loader_feeds_trainer_loss():
    import jax.numpy as jnp

    from deep_vision_tpu.tasks.detection import YoloTask

    samples = synthetic_detection_dataset(4, image_size=64, num_classes=3)
    loader = DetectionLoader(samples, batch_size=2, num_classes=3,
                             image_size=64)
    batch = {k: jnp.asarray(v) for k, v in next(iter(loader)).items()}
    task = YoloTask(3)
    outputs = [jnp.zeros((2, g, g, 3, 8)) for g in (8, 4, 2)]
    loss, comps = task.loss(outputs, batch)
    assert np.isfinite(float(loss)) and float(loss) > 0
