"""Detection data pipeline + dvrec record format tests."""

import numpy as np
import pytest

from deep_vision_tpu.data.detection import (
    DetectionLoader,
    flip_boxes_lr,
    random_crop_with_boxes,
    synthetic_detection_dataset,
)
from deep_vision_tpu.data.records import (
    load_detection_records,
    read_records,
    write_detection_records,
)


def test_flip_boxes():
    b = np.array([[0.1, 0.2, 0.4, 0.6]], np.float32)
    f = flip_boxes_lr(b)
    np.testing.assert_allclose(f, [[0.6, 0.2, 0.9, 0.6]], atol=1e-6)
    np.testing.assert_allclose(flip_boxes_lr(f), b, atol=1e-6)


def test_random_crop_preserves_all_boxes():
    """Reference semantics (YOLO/tensorflow/preprocess.py:52-119): the crop
    margins are sampled between the hull of all boxes and the image edges,
    so EVERY box survives in full and renormalized coords stay in [0,1]."""
    rng = np.random.default_rng(0)
    img = np.arange(100 * 100 * 3, dtype=np.uint8).reshape(100, 100, 3)
    boxes = np.array([[0.4, 0.4, 0.6, 0.6],
                      [0.1, 0.55, 0.3, 0.9]], np.float32)
    for _ in range(50):
        crop, new_boxes, keep = random_crop_with_boxes(img, boxes, rng)
        assert keep.all() and len(new_boxes) == len(boxes)
        assert (new_boxes >= 0).all() and (new_boxes <= 1).all()
        # widths/heights only grow in normalized coords (denominator < 1)
        assert (new_boxes[:, 2] - new_boxes[:, 0]
                >= boxes[:, 2] - boxes[:, 0] - 1e-6).all()
        # crop is strictly within the original image
        assert crop.shape[0] <= 100 and crop.shape[1] <= 100


def test_random_crop_delta_formula():
    """Pin the renormalization math: new = (old - lo) / (1 - lo - hi)."""

    class FixedRng:
        def __init__(self, vals):
            self.vals = list(vals)

        def uniform(self, lo, hi):
            v = self.vals.pop(0)
            assert lo <= v <= max(hi, lo + 1e-12), (v, lo, hi)
            return v

    img = np.zeros((200, 200, 3), np.uint8)
    boxes = np.array([[0.2, 0.3, 0.8, 0.7]], np.float32)
    # dx1=0.1, dy1=0.2, dx2=0.1, dy2=0.1
    crop, nb, keep = random_crop_with_boxes(img, boxes,
                                            FixedRng([0.1, 0.2, 0.1, 0.1]))
    np.testing.assert_allclose(
        nb[0], [(0.2 - 0.1) / 0.8, (0.3 - 0.2) / 0.7,
                (0.8 - 0.1) / 0.8, (0.7 - 0.2) / 0.7], atol=1e-6)
    assert crop.shape[:2] == (140, 160)  # ceil(0.7*200), ceil(0.8*200)


def test_loader_static_shapes():
    samples = synthetic_detection_dataset(8, image_size=64, num_classes=3)
    loader = DetectionLoader(samples, batch_size=4, num_classes=3,
                             image_size=64)
    batch = next(iter(loader))
    assert batch["image"].shape == (4, 64, 64, 3)
    assert batch["y_true_0"].shape == (4, 8, 8, 3, 8)
    assert batch["y_true_2"].shape == (4, 2, 2, 3, 8)
    assert batch["boxes"].shape == (4, 100, 4)
    assert batch["boxes_mask"].sum() >= 4  # ≥1 box per image


def test_records_roundtrip(tmp_path):
    samples = synthetic_detection_dataset(6, image_size=48, num_classes=2)
    write_detection_records(samples, str(tmp_path), "train", num_shards=2,
                            num_workers=1)
    loaded = load_detection_records(str(tmp_path), "train")
    assert len(loaded) == 6
    # boxes/classes survive exactly; images survive JPEG (lossy) decode
    orig_boxes = sorted(tuple(np.round(b, 5)) for s in samples
                        for b in s["boxes"])
    got_boxes = sorted(tuple(np.round(b, 5)) for s in loaded
                       for b in s["boxes"])
    assert orig_boxes == got_boxes
    img = loaded[0]["image"]
    assert img.shape == (48, 48, 3) and img.dtype == np.uint8


def test_records_reject_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_detection_records(str(tmp_path), "val")


def test_raw_store_detection_roundtrip(tmp_path):
    """--store raw: decode-free read path — pixels come back EXACTLY
    (no JPEG loss), shorter side at the build-time resize, labels
    unchanged (boxes are normalized), and the loader trains off it."""
    samples = synthetic_detection_dataset(6, image_size=48, num_classes=2)
    write_detection_records(samples, str(tmp_path), "train", num_shards=2,
                            num_workers=1, store="raw", resize=48)
    loaded = load_detection_records(str(tmp_path), "train")
    assert len(loaded) == 6
    # exact pixels (48² input, resize 48 → stored verbatim); order is
    # round-robin across 2 shards: shard0 gets items 0,2,4
    np.testing.assert_array_equal(loaded[0]["image"], samples[0]["image"])
    got_boxes = sorted(tuple(np.round(b, 5)) for s in loaded
                       for b in s["boxes"])
    orig_boxes = sorted(tuple(np.round(b, 5)) for s in samples
                        for b in s["boxes"])
    assert got_boxes == orig_boxes
    loader = DetectionLoader(loaded, batch_size=3, num_classes=2,
                             image_size=48, train=True, seed=0)
    batch = next(iter(loader))
    assert batch["image"].shape == (3, 48, 48, 3)


def test_raw_store_pose_rescales_pixel_labels(tmp_path):
    """Pose raw store: keypoints/center/scale are pixel-space, so the
    build-time rescale must scale them by the per-axis resize factors."""
    from deep_vision_tpu.data.pose import PoseLoader
    from deep_vision_tpu.data.records import (
        load_pose_records,
        write_pose_records,
    )

    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (96, 128, 3), dtype=np.uint8)
    kp = np.array([[64.0, 48.0, 2.0], [10.0, 90.0, 0.0]], np.float32)
    sample = {"image": img, "keypoints": kp,
              "center": np.array([64.0, 48.0], np.float32), "scale": 0.6}
    write_pose_records([sample], str(tmp_path), "train", num_shards=1,
                       num_workers=1, store="raw", resize=48)
    (got,) = load_pose_records(str(tmp_path), "train")
    assert got["image"].shape == (48, 64, 3)  # shorter side 96 → 48
    fy, fx = 48 / 96, 64 / 128
    np.testing.assert_allclose(got["keypoints"][:, 0], kp[:, 0] * fx,
                               rtol=1e-6)
    np.testing.assert_allclose(got["keypoints"][:, 1], kp[:, 1] * fy,
                               rtol=1e-6)
    np.testing.assert_array_equal(got["keypoints"][:, 2], kp[:, 2])
    np.testing.assert_allclose(got["center"], [64 * fx, 48 * fy])
    np.testing.assert_allclose(got["scale"], 0.6 * fy, rtol=1e-6)
    loader = PoseLoader([got] * 4, batch_size=4, image_size=32,
                        heatmap_size=8, num_keypoints=2, train=True)
    batch = next(iter(loader))
    assert batch["image"].shape == (4, 32, 32, 3)
    assert batch["heatmaps"].shape == (4, 8, 8, 2)


def test_loader_feeds_trainer_loss():
    import jax.numpy as jnp

    from deep_vision_tpu.tasks.detection import YoloTask

    samples = synthetic_detection_dataset(4, image_size=64, num_classes=3)
    loader = DetectionLoader(samples, batch_size=2, num_classes=3,
                             image_size=64)
    batch = {k: jnp.asarray(v) for k, v in next(iter(loader)).items()}
    task = YoloTask(3)
    outputs = [jnp.zeros((2, g, g, 3, 8)) for g in (8, 4, 2)]
    loss, comps = task.loss(outputs, batch)
    assert np.isfinite(float(loss)) and float(loss) > 0


def test_loader_pool_matches_sequential():
    """Per-item rng derives from (seed, epoch, sample_index), so a
    2-worker pool must produce byte-identical batches to inline prep —
    augmentation included."""
    samples = synthetic_detection_dataset(8, image_size=64, num_classes=3)
    seq = DetectionLoader(samples, batch_size=4, num_classes=3,
                          image_size=64, train=True, augment=True, seed=3)
    pooled = DetectionLoader(samples, batch_size=4, num_classes=3,
                             image_size=64, train=True, augment=True,
                             seed=3, num_workers=2)
    try:
        for a, b in zip(seq, pooled):
            assert a.keys() == b.keys()
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
    finally:
        pooled.close()


def test_loader_device_normalize_uint8_parity():
    """device_normalize yields uint8 batches; scaling them on "device"
    (make_scale_preprocess) must reproduce the host-normalized floats."""
    import jax.numpy as jnp

    from deep_vision_tpu.ops.preprocess import make_scale_preprocess

    samples = synthetic_detection_dataset(4, image_size=64, num_classes=3)
    host = DetectionLoader(samples, batch_size=4, num_classes=3,
                           image_size=64, train=True, augment=True, seed=5)
    dev = DetectionLoader(samples, batch_size=4, num_classes=3,
                          image_size=64, train=True, augment=True, seed=5,
                          device_normalize=True)
    hb, db = next(iter(host)), next(iter(dev))
    assert db["image"].dtype == np.uint8
    fn = make_scale_preprocess()
    out = fn({"image": jnp.asarray(db["image"])}, None, True)
    np.testing.assert_allclose(np.asarray(out["image"]), hb["image"],
                               atol=1e-6)
    # labels identical: same rng stream regardless of normalize mode
    np.testing.assert_array_equal(hb["y_true_0"], db["y_true_0"])


def test_loader_pool_with_lazy_records(tmp_path):
    """Offset-based lazy record samples must pickle to pool workers
    (no payload bytes shipped) and produce batches identical to the
    sequential path."""
    samples = synthetic_detection_dataset(6, image_size=48, num_classes=2)
    write_detection_records(samples, str(tmp_path), "train", num_shards=2,
                            num_workers=1)
    lazy = load_detection_records(str(tmp_path), "train")
    seq = DetectionLoader(lazy, batch_size=3, num_classes=2, image_size=48,
                          train=True, augment=True, seed=2)
    pooled = DetectionLoader(lazy, batch_size=3, num_classes=2,
                             image_size=48, train=True, augment=True,
                             seed=2, num_workers=2)
    try:
        for a, b in zip(seq, pooled):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
    finally:
        pooled.close()
    # memory contract: no decoded image retained on the shared samples
    assert not any(dict.__contains__(s, "image") for s in lazy)
