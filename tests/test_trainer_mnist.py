"""End-to-end slice: train/eval/checkpoint/resume on synthetic MNIST over an
8-device data-parallel mesh (SURVEY §7 step 1 accept test, scaled to CI)."""

import numpy as np
import pytest

from deep_vision_tpu.core.config import get_config
from deep_vision_tpu.core.trainer import Trainer
from deep_vision_tpu.data.loader import ArrayLoader
from deep_vision_tpu.data.mnist import synthetic_mnist
from deep_vision_tpu.tasks.classification import ClassificationTask


def make_trainer(tmp_path, mesh, epochs=2):
    cfg = get_config("lenet5")
    cfg.total_epochs = epochs
    cfg.batch_size = 64
    model = cfg.model()
    task = ClassificationTask(num_classes=10)
    return cfg, Trainer(cfg, model, task, mesh=mesh, workdir=str(tmp_path))


def test_scan_steps_smoke(tmp_path, mesh1):
    """Fast-lane coverage of the scanned multi-step dispatch: one epoch at
    scan_steps=2 over 4 batches (2 scanned groups) trains to the right
    step count with finite params.  The exact scan-vs-single trajectory
    equivalence — including the ragged tail — lives in the slow lane
    below."""
    import jax

    cfg = get_config("lenet5")
    cfg.total_epochs = 1
    cfg.batch_size = 32
    cfg.scan_steps = 2
    trainer = Trainer(cfg, cfg.model(), ClassificationTask(10),
                      mesh=mesh1, workdir=str(tmp_path))
    data = synthetic_mnist(128)  # 4 batches of 32 → exactly 2 scanned groups
    state = trainer.fit(ArrayLoader(data, cfg.batch_size, seed=1))
    assert int(jax.device_get(state.step)) == 4
    for leaf in jax.tree_util.tree_leaves(jax.device_get(state.params)):
        assert np.all(np.isfinite(leaf))


@pytest.mark.slow
def test_scan_steps_matches_single_step(tmp_path, mesh1):
    """config.scan_steps=K (K steps per device dispatch via lax.scan) must
    reproduce the step-per-dispatch trajectory EXACTLY — same data order,
    same updates, same final params — including the ragged tail (epoch
    length not divisible by K)."""
    import jax

    data = synthetic_mnist(160)  # 5 batches of 32 → K=2 leaves a tail of 1

    def run(workdir, scan_steps):
        cfg = get_config("lenet5")
        cfg.total_epochs = 2
        cfg.batch_size = 32
        cfg.scan_steps = scan_steps
        trainer = Trainer(cfg, cfg.model(), ClassificationTask(10),
                          mesh=mesh1, workdir=workdir)
        train = ArrayLoader(data, cfg.batch_size, seed=1)
        val = ArrayLoader(data, cfg.batch_size, shuffle=False)
        state = trainer.fit(train, val)
        return state, trainer.evaluate(state, val)

    s1, m1 = run(str(tmp_path / "single"), 1)
    sK, mK = run(str(tmp_path / "scan"), 2)
    assert int(jax.device_get(sK.step)) == int(jax.device_get(s1.step)) == 10
    np.testing.assert_allclose(mK["loss"], m1["loss"], rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        jax.device_get(sK.params), jax.device_get(s1.params))


@pytest.mark.slow
def test_overfits_synthetic(tmp_path, mesh8):
    cfg, trainer = make_trainer(tmp_path, mesh8, epochs=3)
    data = synthetic_mnist(512)
    train = ArrayLoader(data, cfg.batch_size, seed=1)
    val = ArrayLoader(data, cfg.batch_size, shuffle=False)
    state = trainer.fit(train, val)
    metrics = trainer.evaluate(state, val)
    assert metrics["top1"] > 0.9, metrics  # learnable blobs → near-perfect
    assert trainer.logger.latest("val_top1") is not None


@pytest.mark.slow
def test_checkpoint_resume(tmp_path, mesh8):
    cfg, trainer = make_trainer(tmp_path, mesh8, epochs=2)
    data = synthetic_mnist(256)
    train = ArrayLoader(data, 64, seed=1)
    state = trainer.fit(train, None)
    step_after = int(np.asarray(state.step))

    # new trainer on same workdir resumes at epoch 3
    cfg2, trainer2 = make_trainer(tmp_path, mesh8, epochs=2)
    sample = next(iter(train))
    state2 = trainer2.init_state(sample)
    state2 = trainer2.maybe_resume(state2)
    assert int(np.asarray(state2.step)) == step_after
    assert trainer2.start_epoch == 3
    # params actually restored (not re-initialized)
    import jax

    p_trained = jax.device_get(state.params)
    p_restored = jax.device_get(state2.params)
    for a, b in zip(jax.tree_util.tree_leaves(p_trained),
                    jax.tree_util.tree_leaves(p_restored)):
        np.testing.assert_allclose(a, b)


def test_single_device_mesh(tmp_path, mesh1):
    """Everything must run unchanged on one device (the reference's CPU
    fallback `torch.device('cuda' if ... else 'cpu')`)."""
    cfg, trainer = make_trainer(tmp_path, mesh1, epochs=1)
    data = synthetic_mnist(128)
    train = ArrayLoader(data, 32, seed=1)
    state = trainer.fit(train, None)
    assert int(np.asarray(state.step)) == len(train)
