"""`make model-smoke`: boot the multi-model control plane exactly the
way `python -m deep_vision_tpu.cli.serve --models lenet5,yolov3_toy`
does (cli.serve.build_server's plane path), on the CPU host platform
with a weight-cache budget too small to hold both models — then:

  * classify/detect through the per-model path routes
    (/v1/models/{name}/classify|detect) — both models answer 200 even
    though only one fits the HBM budget at a time (evict → spill →
    re-admit under the hood, visible in the cache counters);
  * hot-reload lenet5 MID-LOAD over HTTP (POST
    /v1/models/lenet5/reload {"force": true, "wait": true}) while a
    client thread hammers it — the reload must promote v2 and the
    client must see ZERO errors (the zero-downtime contract, end to
    end through the real HTTP stack);
  * assert /v1/models lists both names with their version tables,
    /v1/stats is plane-shaped (models/cache/plane), and every /metrics
    line parses as Prometheus text exposition — including the
    dvt_serve_model_up and dvt_serve_weight_cache_* series.

Run directly, not under pytest."""

import argparse
import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/model_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# a metric line: name{labels} value  (labels optional; the value is
# validated separately with float(), which accepts nan/inf spellings)
_PROM_LINE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? (\S+)$")


def _post(base, path, payload, timeout=120):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def smoke():
    from deep_vision_tpu.cli.serve import build_server

    with tempfile.TemporaryDirectory() as workdir:
        for name in ("lenet5", "yolov3_toy"):
            os.makedirs(os.path.join(workdir, name), exist_ok=True)
        args = argparse.Namespace(
            model=None, models="lenet5,yolov3_toy", workdir=workdir,
            stablehlo=None, host="127.0.0.1", port=0, max_batch=4,
            max_wait_ms=2.0, buckets=None, max_queue=64, warmup=True,
            verbose=False, pipeline_depth=2, faults="", fault_seed=0,
            serve_devices=1, shard_batches=False, wire_dtype="float32",
            infer_dtype="float32",
            # ~1 MiB holds LeNet (~0.24 MiB) but not the toy YOLO
            # (~2.1 MiB): the cache must evict/spill to serve both
            hbm_budget_mb=1.0,
            canary_frac=0.5, canary_min_requests=3,
            canary_max_error_rate=0.0, canary_max_p99_ratio=50.0,
            shadow_frac=0.0, phase_timeout_s=60.0)
        plane, server = build_server(args)
        server.start_background()
        base = f"http://{server.host}:{server.port}"
        try:
            with urllib.request.urlopen(base + "/v1/healthz",
                                        timeout=60) as r:
                health = json.loads(r.read())
            assert health["status"] == "ok", health
            assert sorted(health["engines"]) == \
                ["lenet5", "yolov3_toy"], health
            # both models answer through the path route, repeatedly —
            # the second round forces the evict→re-admit cycle
            lenet_px = np.zeros((32, 32, 1)).tolist()
            yolo_px = np.zeros((64, 64, 3)).tolist()
            for _ in range(2):
                status, out = _post(base, "/v1/models/lenet5/classify",
                                    {"pixels": lenet_px})
                assert status == 200 and len(out["top"]) == 5, out
                status, out = _post(base, "/v1/models/yolov3_toy/detect",
                                    {"pixels": yolo_px})
                assert status == 200 and "detections" in out, out
            # the model table before the reload
            with urllib.request.urlopen(base + "/v1/models",
                                        timeout=60) as r:
                table = json.loads(r.read())["models"]
            assert table["lenet5"]["active_version"] == 1, table
            assert table["yolov3_toy"]["active_version"] == 1, table

            # hot-reload lenet5 while a client hammers it: zero errors
            errors, served = [], [0]
            stop = threading.Event()

            def hammer():
                while not stop.is_set():
                    try:
                        status, out = _post(
                            base, "/v1/models/lenet5/classify",
                            {"pixels": lenet_px}, timeout=60)
                        assert status == 200 and out["top"], out
                        served[0] += 1
                    except Exception as e:  # noqa: BLE001 — any failure is a lost request
                        errors.append(repr(e))

            t = threading.Thread(target=hammer, daemon=True)
            t.start()
            while served[0] < 5:  # canary traffic needs a live stream
                time.sleep(0.01)
            status, out = _post(base, "/v1/models/lenet5/reload",
                                {"force": True, "wait": True})
            stop.set()
            t.join(60)
            assert status == 200, out
            assert out["status"] == "done", out
            assert out["version"]["version"] == 2, out
            assert out["version"]["state"] == "active", out
            assert not errors, f"reload lost {len(errors)}: {errors[:3]}"

            # plane-shaped stats with live cache counters
            with urllib.request.urlopen(base + "/v1/stats",
                                        timeout=60) as r:
                stats = json.loads(r.read())
            assert set(stats) >= {"models", "cache", "plane"}, set(stats)
            assert stats["models"]["lenet5"]["active_version"] == 2
            assert stats["plane"]["promotions"] == 1, stats["plane"]
            cache = stats["cache"]
            assert cache["evictions"] >= 1, cache
            assert cache["spilled_bytes_total"] > 0, cache

            # /metrics: every line parses; the model/cache series exist
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=60) as r:
                text = r.read().decode()
            for line in text.splitlines():
                if not line or line.startswith("#"):
                    continue
                m = _PROM_LINE.match(line)
                assert m, f"bad metric line: {line}"
                float(m.group(2))  # ValueError = unparseable sample
            assert ('dvt_serve_model_up{model="lenet5",state="active",'
                    'version="2"} 1') in text, \
                "missing model_up for the promoted version"
            assert 'dvt_serve_model_up{model="yolov3_toy"' in text
            for series in ("dvt_serve_weight_cache_budget_bytes",
                           "dvt_serve_weight_cache_hits_total",
                           "dvt_serve_weight_cache_evictions_total",
                           "dvt_serve_reloads_total",
                           "dvt_serve_promotions_total"):
                assert series in text, f"missing {series}"
            print(f"model-smoke PASS: 2 models on a "
                  f"{args.hbm_budget_mb} MiB budget from port "
                  f"{server.port}; reload under load promoted v2 with "
                  f"{served[0]} client requests and 0 errors; cache "
                  f"hits={cache['hits']} misses={cache['misses']} "
                  f"evictions={cache['evictions']} "
                  f"spilled={cache['spilled_bytes_total']}B; "
                  f"{len(text.splitlines())} metric lines parsed")
        finally:
            server.shutdown()
            plane.stop(drain_deadline=5.0)
    return 0


def main():
    # pin the platform before jax initializes (site config can override
    # the env var alone, so set it at the config level too)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    return smoke()


if __name__ == "__main__":
    sys.exit(main())
