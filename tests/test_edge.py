"""Async-edge contract (CPU, tier-1 fast): the selector event loop
serves keep-alive and pipelined HTTP/1.1 with bounded connections and
the threaded server's exact deadline semantics; the content-addressed
response cache answers byte-identical 200s and invalidates through the
version digest in its key; tenant QoS meters quotas before the cache
and sheds by class weight on engine pressure; the gateway reuses pooled
backend connections and pins identical payloads via rendezvous hashing.

Unit tests drive a trivial echo handler (no model, no compile); the
end-to-end tests reuse the LeNet random-init fixture from
test_serve.py's playbook."""

import contextlib
import hashlib
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from deep_vision_tpu.serve.admission import TENANT_HEADER, TenantQoS
from deep_vision_tpu.serve.cache import ResponseCache, payload_digest
from deep_vision_tpu.serve.edge import EdgeServer
from deep_vision_tpu.serve.engine import BatchingEngine
from deep_vision_tpu.serve.faults import FaultPlane
from deep_vision_tpu.serve.gateway import Gateway
from deep_vision_tpu.serve.registry import ModelRegistry

pytestmark = pytest.mark.edge


# -- harness ---------------------------------------------------------------


class _EchoHandler(BaseHTTPRequestHandler):
    """Minimal routes for loop-level tests: GET echoes the path, POST
    echoes the body — same BaseHTTPRequestHandler surface the real
    tiers run through the shim."""

    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _reply(self, payload):
        blob = json.dumps(payload).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        self.wfile.write(blob)

    def do_GET(self):
        if self.path == "/boom":
            raise RuntimeError("handler bug")
        self._reply({"path": self.path})

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        self._reply({"echo": self.rfile.read(n).decode()})


@contextlib.contextmanager
def _edge(handler_cls=_EchoHandler, attrs=None, **kw):
    srv = EdgeServer(("127.0.0.1", 0), handler_cls, **kw)
    for k, v in (attrs or {}).items():
        setattr(srv, k, v)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield srv
    finally:
        srv.shutdown()
        srv.server_close()
        t.join(5)


def _read_response(f) -> tuple[bytes, bytes]:
    """Read exactly one framed HTTP response (status line, body) from a
    socket makefile — a BUFFERED reader, so back-to-back pipelined
    responses aren't lost between reads."""
    status = f.readline().rstrip()
    length = 0
    while True:
        line = f.readline()
        if line in (b"", b"\r\n", b"\n"):
            break
        k, _, v = line.partition(b":")
        if k.strip().lower() == b"content-length":
            length = int(v.strip())
    return status, f.read(length) if length else b""


# -- event loop ------------------------------------------------------------


def test_keepalive_reuses_one_connection():
    """N requests on one HTTPConnection = one accept, N-1 reuses."""
    with _edge() as srv:
        conn = HTTPConnection("127.0.0.1", srv.server_address[1],
                              timeout=5)
        try:
            for i in range(3):
                conn.request("GET", f"/r{i}")
                r = conn.getresponse()
                assert r.status == 200
                assert json.loads(r.read())["path"] == f"/r{i}"
        finally:
            conn.close()
        s = srv.stats()
        assert s["accepted"] == 1
        assert s["requests"] == 3
        assert s["keepalive_reuses"] == 2


def test_pipelined_requests_answer_in_order():
    """Two requests shipped in ONE write come back as two responses in
    request order, even though workers may finish out of order."""
    with _edge() as srv:
        sock = socket.create_connection(
            ("127.0.0.1", srv.server_address[1]))
        sock.settimeout(5)
        f = sock.makefile("rb")
        try:
            sock.sendall(b"GET /first HTTP/1.1\r\nHost: x\r\n\r\n"
                         b"GET /second HTTP/1.1\r\nHost: x\r\n\r\n")
            for expect in ("/first", "/second"):
                status, body = _read_response(f)
                assert b"200" in status
                assert json.loads(body)["path"] == expect
        finally:
            sock.close()
        assert srv.stats()["requests"] == 2


def test_slow_loris_closed_silently():
    """No complete request line by the deadline → EOF, no status."""
    with _edge(attrs={"socket_timeout_s": 0.3}) as srv:
        sock = socket.create_connection(
            ("127.0.0.1", srv.server_address[1]))
        sock.settimeout(5)
        try:
            sock.sendall(b"GET /nev")  # ...stall mid request line
            assert sock.recv(1) == b""  # server hung up, said nothing
        finally:
            sock.close()
        s = srv.stats()
        assert s["closed_idle"] >= 1
        assert s["timeouts_408"] == 0


def test_stalled_body_answers_408():
    """Complete headers + stalled body → explicit 408, then close."""
    with _edge(attrs={"socket_timeout_s": 0.3}) as srv:
        sock = socket.create_connection(
            ("127.0.0.1", srv.server_address[1]))
        sock.settimeout(5)
        f = sock.makefile("rb")
        try:
            sock.sendall(b"POST /x HTTP/1.1\r\nHost: x\r\n"
                         b"Content-Length: 100\r\n\r\n{\"sta")
            status, body = _read_response(f)
            assert b"408" in status
            assert b"timed out" in body
        finally:
            sock.close()
        assert srv.stats()["timeouts_408"] == 1


def test_overlong_head_answers_431():
    with _edge() as srv:
        sock = socket.create_connection(
            ("127.0.0.1", srv.server_address[1]))
        sock.settimeout(5)
        f = sock.makefile("rb")
        try:
            sock.sendall(b"GET / HTTP/1.1\r\nX-Pad: "
                         + b"a" * (70 * 1024))
            status, _ = _read_response(f)
            assert b"431" in status
        finally:
            sock.close()
        assert srv.stats()["overlong_heads"] == 1


def test_malformed_request_line_answers_400():
    with _edge() as srv:
        sock = socket.create_connection(
            ("127.0.0.1", srv.server_address[1]))
        sock.settimeout(5)
        f = sock.makefile("rb")
        try:
            sock.sendall(b"ONE TWO THREE FOUR\r\n\r\n")
            status, _ = _read_response(f)
            assert b"400" in status
        finally:
            sock.close()


def test_unsupported_method_answers_501():
    with _edge() as srv:
        conn = HTTPConnection("127.0.0.1", srv.server_address[1],
                              timeout=5)
        try:
            conn.request("PATCH", "/x")
            assert conn.getresponse().status == 501
        finally:
            conn.close()


def test_handler_exception_answers_500_not_hang():
    """A bug in a route answers 500 and closes — the slot can't wedge
    the connection's response pipeline."""
    with _edge() as srv:
        conn = HTTPConnection("127.0.0.1", srv.server_address[1],
                              timeout=5)
        try:
            conn.request("GET", "/boom")
            r = conn.getresponse()
            assert r.status == 500
            assert "handler bug" in json.loads(r.read())["error"]
        finally:
            conn.close()


def test_max_connections_evicts_oldest_idle():
    """At the ceiling, a new client displaces the longest-idle
    keep-alive connection instead of being refused."""
    with _edge(max_connections=2) as srv:
        port = srv.server_address[1]

        def _get(sock, f, path):
            sock.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n"
                         .encode())
            status, _ = _read_response(f)
            assert b"200" in status

        c1 = socket.create_connection(("127.0.0.1", port))
        c1.settimeout(5)
        _get(c1, c1.makefile("rb"), "/a")  # idle — the eviction victim
        c2 = socket.create_connection(("127.0.0.1", port))
        c2.settimeout(5)
        _get(c2, c2.makefile("rb"), "/b")
        c3 = socket.create_connection(("127.0.0.1", port))
        c3.settimeout(5)
        _get(c3, c3.makefile("rb"), "/c")  # third over a ceiling of two
        assert c1.recv(1) == b""  # oldest idle connection evicted
        for c in (c1, c2, c3):
            c.close()
        assert srv.stats()["evicted_idle"] >= 1


def test_accept_pauses_when_no_connection_is_idle():
    """Ceiling reached with NO idle victim → accepting pauses (instead
    of unbounded growth) and resumes the moment a slot frees."""
    entered, release = threading.Event(), threading.Event()

    class _BlockHandler(_EchoHandler):
        def do_GET(self):
            if self.path == "/block":
                entered.set()
                release.wait(10)
            self._reply({"path": self.path})

    with _edge(_BlockHandler, max_connections=1) as srv:
        port = srv.server_address[1]
        c1 = socket.create_connection(("127.0.0.1", port))
        c1.settimeout(10)
        f1 = c1.makefile("rb")
        c1.sendall(b"GET /block HTTP/1.1\r\nHost: x\r\n\r\n")
        assert entered.wait(5)  # c1 now has an in-flight request: NOT
        c2 = socket.create_connection(("127.0.0.1", port))  # evictable
        c2.settimeout(10)
        f2 = c2.makefile("rb")
        c2.sendall(b"GET /queued HTTP/1.1\r\nHost: x\r\n\r\n")
        deadline = time.monotonic() + 5
        while srv.stats()["accept_pauses"] < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.stats()["accept_pauses"] >= 1
        assert srv.stats()["accepted"] == 1  # c2 is still waiting
        release.set()
        status, _ = _read_response(f1)
        assert b"200" in status
        # close the makefile too: the socket FD (and the FIN the server
        # is waiting for) survives until the last reference drops
        f1.close()
        c1.close()  # slot frees → accepting resumes → c2 served
        status, body = _read_response(f2)
        assert b"200" in status
        assert json.loads(body)["path"] == "/queued"
        c2.close()


# -- response cache --------------------------------------------------------


def test_cache_roundtrip_hits_and_stats():
    c = ResponseCache(max_bytes=1024)
    k = ResponseCache.key("/v1/classify", "m", "digest1", "uint8",
                          "float32", payload_digest(b"body"))
    assert c.get(k) is None
    c.put(k, b"answer")
    assert c.get(k) == b"answer"
    s = c.stats()
    assert (s["hits"], s["misses"], s["insertions"]) == (1, 1, 1)
    assert s["hit_rate"] == 0.5
    assert s["entries"] == 1 and s["bytes"] == 6


def test_cache_key_separates_route_version_and_dtype():
    """Same payload, different route / version digest / dtype → four
    distinct entries: promote changes the digest, so stale answers are
    structurally unreachable rather than explicitly flushed."""
    base = ("/v1/classify", "m", "v1", "uint8", "float32",
            payload_digest(b"img"))
    variants = [
        ResponseCache.key(*base),
        ResponseCache.key("/v1/detect", *base[1:]),
        ResponseCache.key(base[0], base[1], "v2", *base[3:]),
        ResponseCache.key(*base[:3], "float32", *base[4:]),
    ]
    assert len(set(variants)) == 4


def test_cache_lru_eviction_is_byte_bounded():
    c = ResponseCache(max_bytes=100)
    ka, kb, kc = (ResponseCache.key("/r", "m", "v", "u8", "f32", d)
                  for d in ("a", "b", "c"))
    c.put(ka, b"x" * 40)
    c.put(kb, b"y" * 40)
    assert c.get(ka) is not None  # refresh a: b becomes LRU
    c.put(kc, b"z" * 40)          # 120 bytes > 100 → evict b
    assert c.get(kb) is None
    assert c.get(ka) is not None and c.get(kc) is not None
    s = c.stats()
    assert s["evictions"] == 1 and s["bytes"] == 80


def test_cache_skips_blobs_over_budget():
    c = ResponseCache(max_bytes=10)
    k = ResponseCache.key("/r", "m", "v", "u8", "f32", "d")
    c.put(k, b"x" * 11)
    assert c.get(k) is None
    assert c.stats()["insertions"] == 0


# -- tenant QoS ------------------------------------------------------------


def test_qos_spec_parse_and_class_mapping():
    qos = TenantQoS.parse(
        "premium:rate=0,shed_at=1.0,tenants=acme|bigco;"
        "best_effort:rate=20,burst=5,shed_at=0.5;"
        "default=best_effort")
    assert qos.class_of("acme").name == "premium"
    assert qos.class_of("bigco").name == "premium"
    assert qos.class_of("anyone-else").name == "best_effort"
    assert qos.class_of("").name == "best_effort"
    assert qos.classes["best_effort"].burst == 5
    with pytest.raises(ValueError):
        TenantQoS.parse("a:rate=1,bogus=2")
    with pytest.raises(ValueError):
        TenantQoS.parse("a:rate=1;default=missing")
    with pytest.raises(ValueError):
        TenantQoS.parse("")


def test_qos_token_bucket_quota():
    """burst tokens up front, then refill at `rate`; a shed carries the
    exact wait until the next token."""
    qos = TenantQoS.parse("metered:rate=10,burst=2,shed_at=1.0")
    t0 = 100.0
    assert qos.check_quota("t", now=t0) is None
    assert qos.check_quota("t", now=t0) is None   # burst of 2 spent
    shed = qos.check_quota("t", now=t0)
    assert shed is not None and shed.reason == "quota"
    assert shed.retry_after_s == pytest.approx(0.1)  # 1 token @ 10/s
    # 0.2s later two tokens have refilled
    assert qos.check_quota("t", now=t0 + 0.2) is None
    # buckets are per TENANT: a different tenant has its own burst
    assert qos.check_quota("other", now=t0) is None
    assert qos.stats()["metered"]["shed_quota"] == 1


def test_qos_unmetered_class_never_quota_sheds():
    qos = TenantQoS.parse("premium:rate=0,shed_at=1.0")
    assert all(qos.check_quota("vip", now=0.0) is None
               for _ in range(100))


def test_qos_pressure_sheds_by_class_weight():
    """Under the same queue pressure the low class sheds first; cache
    hits never reach this check by construction (see _infer_route)."""
    qos = TenantQoS.parse(
        "premium:rate=0,shed_at=0.9,tenants=vip;"
        "best_effort:rate=0,shed_at=0.5;default=best_effort")
    assert qos.check_pressure("joe", 4, 10) is None       # 0.4 < 0.5
    shed = qos.check_pressure("joe", 5, 10)               # 0.5 ≥ 0.5
    assert shed is not None and shed.reason == "priority"
    assert qos.check_pressure("vip", 8, 10) is None       # 0.8 < 0.9
    assert qos.check_pressure("vip", 9, 10) is not None
    assert qos.check_pressure("joe", 5, 0) is None        # no bound
    s = qos.stats()
    assert s["best_effort"]["shed_priority"] == 1
    assert s["premium"]["shed_priority"] == 1


def test_qos_records_latency_and_cache_hits():
    qos = TenantQoS.parse("only:rate=0,shed_at=1.0")
    qos.record_served("t", 0.010)
    qos.record_served("t", 0.020, cache_hit=True)
    s = qos.stats()["only"]
    assert s["served"] == 2 and s["cache_hits"] == 1
    assert s["latency"]["count"] == 2
    assert s["default"] is True


# -- gateway: affinity + pooled connections --------------------------------


def test_affinity_pick_is_deterministic_with_failover():
    """Rendezvous hashing: one payload digest always lands on the same
    backend; excluding it falls to a consistent runner-up; different
    digests spread."""
    gw = Gateway(["127.0.0.1:18001", "127.0.0.1:18002",
                  "127.0.0.1:18003"], probe_interval_s=60,
                 affinity=True)
    key = hashlib.blake2b(b"payload", digest_size=8).digest()
    picks = {gw._pick([], affinity_key=key) for _ in range(10)}
    assert len(picks) == 1
    primary = picks.pop()
    alts = {gw._pick([primary], affinity_key=key).name
            for _ in range(10)}
    assert len(alts) == 1 and alts.pop() != primary.name
    spread = {gw._pick([], affinity_key=hashlib.blake2b(
                  f"p{i}".encode(), digest_size=8).digest()).name
              for i in range(32)}
    assert len(spread) >= 2
    # without a key the pick falls back to least-loaded round-robin
    assert gw._pick([]) is not None


def test_gateway_pools_backend_connections():
    """Forwarding N requests dials the backend once and reuses the
    pooled keep-alive connection for the rest."""
    served = []

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            blob = b'{"status": "ok"}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def do_POST(self):
            served.append(self.path)
            self.rfile.read(
                int(self.headers.get("Content-Length") or 0))
            blob = b'{"ok": true}'
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    gw = Gateway([f"127.0.0.1:{httpd.server_address[1]}"],
                 probe_interval_s=60).start()
    try:
        for _ in range(4):
            status, _, _ = gw.forward("/v1/classify", b'{"x":1}')
            assert status == 200
        b = gw.backends[0]
        assert b.conns_created == 1
        assert b.conns_reused == 3
        assert b.report()["conns"]["created"] == 1
    finally:
        gw.stop()
        httpd.shutdown()
        httpd.server_close()
    assert len(served) == 4


# -- end-to-end over the real serve stack ----------------------------------


@pytest.fixture(scope="module")
def lenet_serving(tmp_path_factory):
    reg = ModelRegistry()
    sm = reg.load_checkpoint(
        "lenet5", str(tmp_path_factory.mktemp("lenet_workdir")))
    return reg, sm


def _classify(base, pixels, headers=None, debug=False,
              want_cache=None):
    body = json.dumps({"pixels": pixels}).encode()
    url = base + "/v1/classify" + ("?debug=1" if debug else "")
    req = urllib.request.Request(
        url, data=body,
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as r:
        if want_cache is not None:
            # the hit/miss wire marker (X-DVT-Cache: hit on hits only)
            got = r.headers.get("X-DVT-Cache") == "hit"
            assert got == want_cache, dict(r.headers)
        return r.status, json.loads(r.read())


def _stats(base):
    with urllib.request.urlopen(base + "/v1/stats", timeout=10) as r:
        return json.loads(r.read())


def test_http_cache_hit_and_version_invalidation(lenet_serving):
    """Identical payloads answer from cache; a promote (new params
    digest) makes every old entry unreachable — never served stale."""
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[4], max_wait_ms=2).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0,
                      response_cache=ResponseCache()).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    pixels = np.zeros((32, 32, 1)).tolist()
    old_digest = sm.params_digest
    try:
        _, first = _classify(base, pixels, want_cache=False)
        served_before = eng.served
        _, second = _classify(base, pixels, want_cache=True)
        assert second == first            # byte-identical answer
        assert eng.served == served_before  # hit consumed no engine
        cs = _stats(base)["response_cache"]
        assert cs["hits"] == 1 and cs["insertions"] == 1
        # model a promote: the active version's digest changes
        sm.params_digest = "ffffffffdeadbeef"
        _, third = _classify(base, pixels, want_cache=False)
        assert third == first             # same weights, fresh compute
        cs = _stats(base)["response_cache"]
        assert cs["hits"] == 1            # old entry never matched
        assert cs["insertions"] == 2
        # debug requests bypass the cache both ways (span is per-req)
        _, dbg = _classify(base, pixels, debug=True)
        assert "trace" in dbg
        assert _stats(base)["response_cache"]["insertions"] == 2
        # edge counters ride the same stats payload and /metrics
        assert _stats(base)["edge"]["accepted"] >= 1
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "dvt_serve_cache_hits_total 1" in text
        assert "dvt_serve_open_connections" in text
    finally:
        sm.params_digest = old_digest
        srv.shutdown()
        eng.stop()


def test_http_failures_are_never_cached(lenet_serving):
    """A quarantined (500) answer must not be replayed from cache: the
    retry after the transient fault recomputes and THEN caches."""
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[4], max_wait_ms=2,
                         faults=FaultPlane("compute:exception:times=1"),
                         retry_budget=0).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0,
                      response_cache=ResponseCache()).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    pixels = np.ones((32, 32, 1)).tolist()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _classify(base, pixels)
        assert exc.value.code == 500
        assert _stats(base)["response_cache"]["insertions"] == 0
        status, _ = _classify(base, pixels)  # fault spent: serves fine
        assert status == 200
        assert _stats(base)["response_cache"]["insertions"] == 1
    finally:
        srv.shutdown()
        eng.stop()


def test_http_tenant_qos_sheds_by_class(lenet_serving):
    """X-DVT-Tenant maps to a class; the starved class 429s (with
    Retry-After) while the premium class keeps being served, and sheds
    are never inserted into the cache."""
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[4], max_wait_ms=2).start()
    qos = TenantQoS.parse(
        "premium:rate=0,shed_at=1.0,tenants=vip;"
        "bronze:rate=0,shed_at=0.0;default=bronze")
    srv = ServeServer(reg, {sm.name: eng}, port=0, qos=qos,
                      response_cache=ResponseCache()).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    pixels = np.zeros((32, 32, 1)).tolist()
    try:
        status, _ = _classify(base, pixels, {TENANT_HEADER: "vip"})
        assert status == 200
        # shed_at=0.0: any cache MISS sheds the bronze class
        with pytest.raises(urllib.error.HTTPError) as exc:
            _classify(base, np.ones((32, 32, 1)).tolist(),
                      {TENANT_HEADER: "joe"})
        assert exc.value.code == 429
        assert "priority" in json.loads(exc.value.read())["error"]
        assert exc.value.headers["Retry-After"] is not None
        # ... but a cache HIT costs no engine capacity: bronze may have it
        status, _ = _classify(base, pixels, {TENANT_HEADER: "joe"})
        assert status == 200
        qs = _stats(base)["qos"]
        assert qs["premium"]["served"] == 1
        assert qs["bronze"]["shed_priority"] == 1
        assert qs["bronze"]["cache_hits"] == 1
        cs = _stats(base)["response_cache"]
        assert cs["insertions"] == 1      # the shed was never cached
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert 'dvt_serve_tenant_shed_total{class="bronze",' \
               'reason="priority"} 1' in text
        assert 'dvt_serve_tenant_served_total{class="premium"} 1' \
               in text
    finally:
        srv.shutdown()
        eng.stop()
