"""Device-side detect decode (CPU, tier-1): the fused detect epilogue
(serve/workloads.DetectWorkload.make_epilogue) traces decode → score
floor → pre-NMS top-k → class-wise NMS into the AOT bucket programs so
the drainer's bulk D2H ships K fixed-size boxes per image instead of
the dense multi-scale pyramid.  Covered here:

  * epilogue-vs-host-postprocess parity (identical kept set, scores
    within 1e-5) on single-device, replicated, and 1×4 mesh engines
    (conftest pins 8 virtual CPU devices);
  * the ≥100× D2H reduction gate at the REAL 416² pyramid shape,
    asserted from the engine's ``d2h_bytes_by_bucket`` counters;
  * trim-by-valid ``respond``: ``num_detections``, no padded/invalid
    rows, >= semantics at the threshold edge, empty-image answers;
  * Soft-NMS (gaussian/linear decay) + per-class K suppression
    variants: ops/boxes unit semantics, epilogue-vs-host parity with
    the knobs on, bit-identity of the hard path at default knobs;
  * CenterNet through the same hook (family-switched decode, NMS-free);
  * the detect shadow-agreement rule (greedy IoU≥0.5 class-matched
    pairing): perfect / shifted / class-swapped / empty pairs;
  * detect response-cache hits over real HTTP via ``X-DVT-Cache``.

Heavyweight compiles live in module-scoped fixtures, one per config."""

import copy
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from deep_vision_tpu.ops.boxes import batched_nms, nms_single
from deep_vision_tpu.serve.engine import BatchingEngine
from deep_vision_tpu.serve.registry import ModelRegistry
from deep_vision_tpu.serve.workloads import WORKLOADS

pytestmark = pytest.mark.serve

DETECT = WORKLOADS["detect"]
#: fixed-size epilogue row: K·(16 + 4 + 4 + 4) bytes per image
ROW_BYTES_PER_K = 16 + 4 + 4 + 4


@pytest.fixture(scope="module")
def yolo_serving(tmp_path_factory):
    reg = ModelRegistry()
    # empty workdir fixture → deterministic PRNGKey(0) random init
    sm = reg.load_checkpoint(
        "yolov3_toy", str(tmp_path_factory.mktemp("yolo_workdir")))
    return reg, sm


@pytest.fixture(scope="module")
def yolo416_serving(tmp_path_factory):
    reg = ModelRegistry()
    sm = reg.load_checkpoint(
        "yolov3_toy416", str(tmp_path_factory.mktemp("yolo416_workdir")))
    return reg, sm


@pytest.fixture(scope="module")
def centernet_serving(tmp_path_factory):
    reg = ModelRegistry()
    sm = reg.load_checkpoint(
        "centernet_toy", str(tmp_path_factory.mktemp("cn_workdir")))
    return reg, sm


def _host_view(sm):
    """The A/B baseline: same weights, epilogue disabled — dense
    pyramid rows decoded host-side (the detect_decode knob the way
    tests/test_workloads.py pins generate's output_wire)."""
    sm_host = copy.copy(sm)
    sm_host.detect_decode = "host"
    return sm_host


def _images(n, size):
    return np.random.RandomState(0).randn(
        n, size, size, 3).astype(np.float32)


# -- parity: fused epilogue == host postprocess ----------------------------


def test_epilogue_vs_host_postprocess_parity(yolo_serving):
    """The device-decoded rows must match host ``postprocess`` over the
    dense pyramid: identical kept set (classes + valid), boxes/scores
    within 1e-5 — same knobs on both paths."""
    import jax

    from deep_vision_tpu.tasks.detection import postprocess

    _, sm = yolo_serving
    x = _images(2, 64)
    dev = jax.device_get(sm.compile_bucket(2)(x))
    assert set(dev) == {"boxes", "scores", "classes", "valid"}
    k = sm.detect_topk
    assert np.asarray(dev["boxes"]).shape == (2, k, 4)
    assert np.asarray(dev["classes"]).dtype == np.int32

    pyr = jax.device_get(_host_view(sm).compile_bucket(2)(x))
    boxes, scores, classes, valid = postprocess(
        pyr, sm.num_classes, max_outputs=sm.detect_topk,
        iou_threshold=sm.detect_iou_threshold,
        score_threshold=sm.detect_score_threshold, class_aware=True)
    np.testing.assert_allclose(np.asarray(dev["boxes"]),
                               np.asarray(boxes), atol=1e-5)
    np.testing.assert_allclose(np.asarray(dev["scores"]),
                               np.asarray(scores), atol=1e-5)
    np.testing.assert_array_equal(np.asarray(dev["classes"]),
                                  np.asarray(classes))
    np.testing.assert_array_equal(np.asarray(dev["valid"]),
                                  np.asarray(valid))

    # respond() over either row shape answers identically
    row_dev = {key: np.asarray(v)[0] for key, v in dev.items()}
    row_host = [np.asarray(a)[0] for a in pyr]
    r_dev = DETECT.respond(sm, {"score_threshold": 0.1}, row_dev)
    r_host = DETECT.respond(_host_view(sm), {"score_threshold": 0.1},
                            row_host)
    assert r_dev["num_detections"] == r_host["num_detections"]
    assert r_dev["detections"] == r_host["detections"]


def test_replicated_and_mesh_engines_bit_identical(yolo_serving):
    """for_device and 1×4 (data×model) mesh views of the same weights
    produce BIT-identical device-decoded rows: tiny-yolo leaves sit
    under the fallback sharder's min dim, so the mesh replicates and
    the fused epilogue math is the same program."""
    import jax

    from deep_vision_tpu.parallel.mesh import make_mesh

    _, sm = yolo_serving
    x = _images(2, 64)
    base = jax.device_get(sm.compile_bucket(2)(x))
    devs = jax.devices()
    views = {"replicated": sm.for_device(devs[1]),
             "mesh_1x4": sm.for_mesh(
                 make_mesh({"data": 1, "model": 4}, devices=devs[:4]))}
    for label, view in views.items():
        out = jax.device_get(view.compile_bucket(2)(x))
        for key in base:
            assert np.array_equal(np.asarray(base[key]),
                                  np.asarray(out[key])), (label, key)


# -- respond: trim-by-valid formatter --------------------------------------


def test_respond_trims_to_valid_and_counts(yolo_serving):
    _, sm = yolo_serving
    k = sm.detect_topk
    row = {"boxes": np.tile([0.1, 0.1, 0.4, 0.5], (k, 1)
                            ).astype(np.float32),
           "scores": np.linspace(0.9, 0.0, k, dtype=np.float32),
           "classes": np.zeros(k, np.int32),
           "valid": (np.arange(k) < 7).astype(np.float32)}
    out = DETECT.respond(sm, {"score_threshold": 0.5}, row)
    # valid rows 0..6 score 0.9 down to ~0.845 — all clear 0.5; the
    # padded tail (valid=0) must NOT appear
    assert out["num_detections"] == 7
    assert len(out["detections"]) == 7
    assert all(d["score"] >= 0.5 for d in out["detections"])

    # >= at the threshold edge: a request threshold equal to a kept
    # score keeps that box
    edge = float(row["scores"][3])
    out = DETECT.respond(sm, {"score_threshold": edge}, row)
    assert out["num_detections"] == 4
    assert out["detections"][-1]["score"] == pytest.approx(edge)

    # sub-floor request thresholds clamp to the compiled floor (boxes
    # under the floor never survived device NMS)
    low = DETECT.respond(sm, {"score_threshold": 0.0}, row)
    assert low["num_detections"] == 7


def test_empty_image_answers_zero_detections(yolo_serving):
    """A floor no random-init score can reach → all-invalid rows →
    an empty, well-formed response (the empty-image edge)."""
    import jax

    _, sm = yolo_serving
    sm_high = copy.copy(sm)
    sm_high.detect_score_threshold = 2.0  # scores are products of σ's
    out = jax.device_get(sm_high.compile_bucket(1)(_images(1, 64)))
    assert float(np.asarray(out["valid"]).sum()) == 0.0
    row = {key: np.asarray(v)[0] for key, v in out.items()}
    resp = DETECT.respond(sm_high, {}, row)
    assert resp["num_detections"] == 0
    assert resp["detections"] == []


# -- class-wise NMS (ops/boxes) --------------------------------------------


def test_class_wise_nms_suppresses_within_class_only():
    boxes = np.asarray([[0.1, 0.1, 0.5, 0.5],
                        [0.12, 0.12, 0.5, 0.5],   # IoU≈0.9 with box 0
                        [0.7, 0.7, 0.9, 0.9]], np.float32)
    scores = np.asarray([0.9, 0.8, 0.7], np.float32)
    same = np.zeros(3, np.int32)
    mixed = np.asarray([0, 1, 2], np.int32)

    _, _, v_agnostic = nms_single(boxes, scores, 3)
    _, _, v_same = nms_single(boxes, scores, 3, classes=same)
    idx, _, v_mixed = nms_single(boxes, scores, 3, classes=mixed)
    # same class (or no classes): the overlapping pair collapses
    assert v_agnostic.sum() == 2 and v_same.sum() == 2
    # different classes: nothing suppresses across classes
    assert v_mixed.sum() == 3

    # batched wrapper threads classes per image
    _, _, bv = batched_nms(boxes[None], scores[None], 3,
                           classes=mixed[None])
    assert bv.sum() == 3


# -- Soft-NMS + per-class K (ops/boxes suppression variants) ----------------


def _overlap_triplet():
    """Two heavily-overlapping same-class boxes plus one far box."""
    boxes = np.asarray([[0.1, 0.1, 0.5, 0.5],
                        [0.12, 0.12, 0.5, 0.5],
                        [0.7, 0.7, 0.9, 0.9]], np.float32)
    scores = np.asarray([0.9, 0.8, 0.7], np.float32)
    return boxes, scores


def test_soft_nms_gaussian_decays_instead_of_killing():
    """Gaussian Soft-NMS keeps the overlapping neighbour at a decayed
    score exp(-iou²/σ) — the hard path drops it outright — and a
    score floor above the decayed value still kills it."""
    from deep_vision_tpu.ops.boxes import broadcast_iou

    boxes, scores = _overlap_triplet()
    iou01 = float(np.asarray(broadcast_iou(boxes, boxes))[0, 1])
    assert iou01 > 0.5

    _, hard_sel, hard_valid = nms_single(boxes, scores, 3)
    assert hard_valid.sum() == 2  # box 1 suppressed

    idx, sel, valid = nms_single(boxes, scores, 3, soft="gaussian",
                                 soft_sigma=0.5)
    assert valid.sum() == 3  # everyone survives, reordered by decay
    expect = 0.8 * np.exp(-(iou01 ** 2) / 0.5)
    order = {int(i): float(s) for i, s in zip(np.asarray(idx),
                                              np.asarray(sel))}
    assert order[0] == pytest.approx(0.9)
    assert order[2] == pytest.approx(0.7)      # iou 0 → no decay
    assert order[1] == pytest.approx(expect, abs=1e-5)
    # decay reorders: the far 0.7 box now outranks the decayed one
    assert list(np.asarray(idx)) == [0, 2, 1]

    # a floor above the decayed score kills the neighbour after all
    _, _, v_floor = nms_single(boxes, scores, 3, soft="gaussian",
                               soft_sigma=0.5,
                               score_threshold=expect + 0.05)
    assert v_floor.sum() == 2


def test_soft_nms_linear_and_off_and_validation():
    from deep_vision_tpu.ops.boxes import broadcast_iou

    boxes, scores = _overlap_triplet()
    iou01 = float(np.asarray(broadcast_iou(boxes, boxes))[0, 1])

    idx, sel, valid = nms_single(boxes, scores, 3, soft="linear")
    assert valid.sum() == 3
    order = {int(i): float(s) for i, s in zip(np.asarray(idx),
                                              np.asarray(sel))}
    # linear decay only applies past the IoU threshold: (1 - iou)·s
    assert order[1] == pytest.approx(0.8 * (1.0 - iou01), abs=1e-5)
    assert order[2] == pytest.approx(0.7)

    # soft="off" is the bit-identical hard path (the default)
    for a, b in zip(nms_single(boxes, scores, 3),
                    nms_single(boxes, scores, 3, soft="off")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    with pytest.raises(ValueError, match="soft"):
        nms_single(boxes, scores, 3, soft="sigmoid")


def test_per_class_k_caps_within_class_only():
    """max_per_class keeps each class's top-K VALID boxes: a crowd of
    one class cannot monopolize the fixed epilogue rows, other classes
    are untouched."""
    # four disjoint boxes: three of class 0 (crowding), one of class 1
    boxes = np.asarray([[0.0, 0.0, 0.2, 0.2],
                        [0.3, 0.3, 0.5, 0.5],
                        [0.6, 0.6, 0.8, 0.8],
                        [0.0, 0.6, 0.2, 0.8]], np.float32)
    scores = np.asarray([0.9, 0.8, 0.7, 0.6], np.float32)
    classes = np.asarray([0, 0, 0, 1], np.int32)

    _, _, v_uncapped = nms_single(boxes, scores, 4, classes=classes)
    assert v_uncapped.sum() == 4

    idx, sel, valid = nms_single(boxes, scores, 4, classes=classes,
                                 max_per_class=2)
    kept = {int(i) for i, v in zip(np.asarray(idx), np.asarray(valid))
            if v > 0}
    # class 0 keeps its best two (0.9, 0.8); the 0.7 third is cut;
    # class 1's only box rides along
    assert kept == {0, 1, 3}
    # invalidated rows zero their score too
    assert float(np.asarray(sel)[np.asarray(idx) == 2][0]) == 0.0

    # cap without classes is a no-op (nothing to group by)
    _, _, v_nocls = nms_single(boxes, scores, 4, max_per_class=2)
    assert v_nocls.sum() == 4

    # batched wrapper threads the cap
    _, _, bv = batched_nms(boxes[None], scores[None], 4,
                           classes=classes[None], max_per_class=2)
    assert bv.sum() == 3


def test_soft_nms_epilogue_vs_host_parity(yolo_serving):
    """The fused epilogue honours the suppression knobs: device rows
    with gaussian Soft-NMS + per-class K match host ``postprocess``
    with the same knobs, and knobs at their defaults stay bit-identical
    to the baseline program."""
    import jax

    from deep_vision_tpu.tasks.detection import postprocess

    _, sm = yolo_serving
    x = _images(2, 64)

    sm_soft = copy.copy(sm)
    sm_soft.detect_soft_nms = "gaussian"
    sm_soft.detect_soft_sigma = 0.4
    sm_soft.detect_max_per_class = 3
    dev = jax.device_get(sm_soft.compile_bucket(2)(x))

    pyr = jax.device_get(_host_view(sm).compile_bucket(2)(x))
    boxes, scores, classes, valid = postprocess(
        pyr, sm.num_classes, max_outputs=sm.detect_topk,
        iou_threshold=sm.detect_iou_threshold,
        score_threshold=sm.detect_score_threshold, class_aware=True,
        soft_nms="gaussian", soft_sigma=0.4, max_per_class=3)
    np.testing.assert_allclose(np.asarray(dev["boxes"]),
                               np.asarray(boxes), atol=1e-5)
    np.testing.assert_allclose(np.asarray(dev["scores"]),
                               np.asarray(scores), atol=1e-5)
    np.testing.assert_array_equal(np.asarray(dev["classes"]),
                                  np.asarray(classes))
    np.testing.assert_array_equal(np.asarray(dev["valid"]),
                                  np.asarray(valid))

    # default knobs ("off", K=0) leave the program bit-identical
    sm_off = copy.copy(sm)
    sm_off.detect_soft_nms = "off"
    sm_off.detect_max_per_class = 0
    base = jax.device_get(sm.compile_bucket(2)(x))
    off = jax.device_get(sm_off.compile_bucket(2)(x))
    for key in base:
        np.testing.assert_array_equal(np.asarray(base[key]),
                                      np.asarray(off[key]))

    # describe() surfaces the knobs for operators
    desc = sm_soft.describe()["detect"]
    assert desc["soft_nms"] == "gaussian"
    assert desc["soft_sigma"] == pytest.approx(0.4)
    assert desc["max_per_class"] == 3


# -- the ≥100× D2H gate at 416² --------------------------------------------


def test_d2h_reduction_gate_416(yolo416_serving):
    """At the real 416² pyramid (10,647 anchors × 8 channels × 4 B ≈
    340 KB/image dense) the fused epilogue's fixed K-row output must
    cut the drainer's bulk D2H ≥100× — asserted from the engine's own
    ``d2h_bytes_by_bucket`` counters, device-decode engine vs the
    host-path baseline engine over the same weights."""
    _, sm = yolo416_serving
    x = _images(1, 416)[0]

    per_bucket = {}
    for label, model in (("device", sm), ("host", _host_view(sm))):
        eng = BatchingEngine(model, buckets=(1,), max_batch=1)
        eng.start()
        try:
            out = eng.infer(x, timeout=300)
        finally:
            eng.stop()
        if label == "device":
            assert isinstance(out, dict) and "boxes" in out, type(out)
        per_bucket[label] = eng.stats()["pipeline"]["d2h_bytes_by_bucket"]

    dev_bytes = per_bucket["device"][1]
    host_bytes = per_bucket["host"][1]
    # the device row is exactly K·28 B: boxes (K,4) f32 + scores +
    # classes(i32) + valid, nothing else crosses D2H
    assert dev_bytes == sm.detect_topk * ROW_BYTES_PER_K, per_bucket
    assert host_bytes >= 100 * dev_bytes, per_bucket


# -- CenterNet through the same hook ---------------------------------------


def test_centernet_device_decode(centernet_serving):
    """The registry picks the decode by model family: a centernet-task
    model serves /v1/detect with the NMS-free peak decode traced into
    its bucket programs, same fixed-size row contract, boxes
    normalized to [0,1]-space like YOLO's."""
    import jax

    _, sm = centernet_serving
    assert sm.workload.verb == "detect"
    x = _images(2, 64)
    dev = jax.device_get(sm.compile_bucket(2)(x))
    k = sm.detect_topk
    assert np.asarray(dev["boxes"]).shape == (2, k, 4)
    assert np.asarray(dev["scores"]).shape == (2, k)
    # grid-coord decode normalized by G: unit-ish scale, not raw
    # 16²-grid indices (random-init offset heads are unbounded, so
    # only the order of magnitude is stable)
    assert np.abs(np.asarray(dev["boxes"])).max() < 4.0

    # host-path parity: the same decode math runs in respond()
    pyr = jax.device_get(_host_view(sm).compile_bucket(2)(x))
    row_dev = {key: np.asarray(v)[0] for key, v in dev.items()}
    row_host = jax.tree_util.tree_map(lambda a: np.asarray(a)[0], pyr)
    r_dev = DETECT.respond(sm, {"score_threshold": 0.05}, row_dev)
    r_host = DETECT.respond(_host_view(sm), {"score_threshold": 0.05},
                            row_host)
    assert r_dev["num_detections"] == r_host["num_detections"] > 0
    for a, b in zip(r_dev["detections"], r_host["detections"]):
        assert a["class"] == b["class"]
        assert a["score"] == pytest.approx(b["score"], abs=1e-5)
        np.testing.assert_allclose(a["box"], b["box"], atol=1e-3)


# -- shadow agreement: the mAP proxy ---------------------------------------


def _det_row(boxes, classes, scores=None, k=8):
    boxes = np.asarray(boxes, np.float32).reshape(-1, 4)
    n = len(boxes)
    row = {"boxes": np.zeros((k, 4), np.float32),
           "scores": np.zeros(k, np.float32),
           "classes": np.zeros(k, np.int32),
           "valid": np.zeros(k, np.float32)}
    row["boxes"][:n] = boxes
    row["scores"][:n] = np.linspace(0.9, 0.5, n) if scores is None \
        else np.asarray(scores, np.float32)
    row["classes"][:n] = np.asarray(classes, np.int32)
    row["valid"][:n] = 1.0
    return row


def test_detect_shadow_agreement_verdicts():
    from deep_vision_tpu.serve.admission import Shed

    a = _det_row([[0.1, 0.1, 0.3, 0.3], [0.5, 0.5, 0.8, 0.9]], [0, 2])
    # perfect pair: every box IoU=1 with its same-class partner
    assert DETECT.agree(a, a) is True
    # shifted: both boxes displaced past IoU 0.5 → zero matches
    shifted = _det_row([[0.35, 0.35, 0.55, 0.55],
                        [0.05, 0.05, 0.35, 0.45]], [0, 2])
    assert DETECT.agree(a, shifted) is False
    # class-swapped: same geometry, labels exchanged → IoU pairs exist
    # but the class gate rejects them all
    swapped = _det_row([[0.1, 0.1, 0.3, 0.3], [0.5, 0.5, 0.8, 0.9]],
                       [2, 0])
    assert DETECT.agree(a, swapped) is False
    # both empty: a candidate that also finds nothing is consistent
    empty = _det_row(np.zeros((0, 4)), [])
    assert DETECT.agree(empty, empty) is True
    assert DETECT.agree(a, empty) is False
    # count mismatch dilutes the fraction below min_match_frac
    extra = _det_row([[0.1, 0.1, 0.3, 0.3], [0.5, 0.5, 0.8, 0.9],
                      [0.0, 0.6, 0.2, 0.9], [0.6, 0.0, 0.9, 0.2]],
                     [0, 2, 1, 1])
    assert DETECT.agree(a, extra) is False
    # not comparable: Shed-ish rows and dense host pyramids
    assert DETECT.agree(a, Shed("x", "y")) is None
    assert DETECT.agree([np.zeros((8, 8, 3, 8))], a) is None


# -- response cache over real HTTP -----------------------------------------


def test_detect_response_cache_hit(yolo_serving):
    """Small canonical detect payloads are cacheable: a byte-identical
    repeat answers from the response cache (X-DVT-Cache: hit) without
    consuming engine capacity, and carries num_detections."""
    from deep_vision_tpu.serve.cache import ResponseCache
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = yolo_serving
    eng = BatchingEngine(sm, buckets=(1,), max_batch=1)
    eng.start()
    srv = ServeServer(reg, {sm.name: eng}, port=0,
                      response_cache=ResponseCache(1 << 20))
    srv.start_background()
    base = f"http://127.0.0.1:{srv.port}"
    body = json.dumps({"pixels": np.zeros((64, 64, 3)).tolist(),
                       "score_threshold": 0.2}).encode()
    try:
        def post(path):
            req = urllib.request.Request(
                base + path, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                return r.status, dict(r.headers), json.loads(r.read())

        status, headers, first = post("/v1/detect")
        assert status == 200
        assert "num_detections" in first
        assert len(first["detections"]) == first["num_detections"]
        assert headers.get("X-DVT-Cache") != "hit"

        served = eng.served
        status, headers, again = post("/v1/detect")
        assert status == 200
        assert headers.get("X-DVT-Cache") == "hit", headers
        assert again == first
        assert eng.served == served, "cache hit consumed engine capacity"

        # wrong verb still 400s naming the right route
        with pytest.raises(urllib.error.HTTPError) as exc:
            req = urllib.request.Request(
                base + "/v1/classify", data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=60)
        assert exc.value.code == 400
        assert "/v1/detect" in json.loads(exc.value.read())["error"]
    finally:
        srv.shutdown()
        eng.stop()
