"""Pretrained-weight import (the resnet50v2.py:137-153 load_model_weights
role): torchvision-format ResNet state_dicts → flax variables, verified by
FORWARD PARITY against a torch reference network with the same weights.

torchvision itself isn't installed here, so the test builds a minimal
torch ResNet-50 with torchvision's exact module naming
(conv1/bn1/layerX.Y.convZ/bnZ/downsample/fc) and stride placement (V1.5:
stride on the 3×3) — random weights, eval mode — and checks logits match.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from deep_vision_tpu.models.pretrained import (  # noqa: E402
    import_torch_resnet,
    merge_pretrained,
)
from deep_vision_tpu.models.resnet import ResNet50  # noqa: E402


class TorchBottleneck(tnn.Module):
    """torchvision.models.resnet.Bottleneck with fixed expansion 4."""

    def __init__(self, in_ch, width, stride=1):
        super().__init__()
        out_ch = width * 4
        self.conv1 = tnn.Conv2d(in_ch, width, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(width)
        self.conv2 = tnn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(width)
        self.conv3 = tnn.Conv2d(width, out_ch, 1, bias=False)
        self.bn3 = tnn.BatchNorm2d(out_ch)
        self.relu = tnn.ReLU()
        self.downsample = None
        if stride != 1 or in_ch != out_ch:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(in_ch, out_ch, 1, stride, bias=False),
                tnn.BatchNorm2d(out_ch))

    def forward(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return self.relu(y + idt)


class TorchResNet50(tnn.Module):
    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = tnn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = tnn.BatchNorm2d(64)
        self.relu = tnn.ReLU()
        self.maxpool = tnn.MaxPool2d(3, 2, 1)
        in_ch = 64
        for s, (width, blocks) in enumerate(
                [(64, 3), (128, 4), (256, 6), (512, 3)], start=1):
            layers = []
            for i in range(blocks):
                stride = 2 if s > 1 and i == 0 else 1
                layers.append(TorchBottleneck(in_ch, width, stride))
                in_ch = width * 4
            setattr(self, f"layer{s}", tnn.Sequential(*layers))
        self.avgpool = tnn.AdaptiveAvgPool2d(1)
        self.fc = tnn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        for s in (1, 2, 3, 4):
            x = getattr(self, f"layer{s}")(x)
        return self.fc(torch.flatten(self.avgpool(x), 1))


def _randomize_bn_stats(model, gen):
    """Non-trivial running stats AND O(1) affine params so the parity check
    exercises them.  The scales must stay near 1: tiny (0.05·randn) BN scales
    attenuate the residual branch ~1e-4 relative to the shortcut and MASK
    real semantic mismatches (this hid a stride-2 padding bug — SAME pads
    low=0/high=1 where torch effectively pads low=1 — until round 5)."""
    with torch.no_grad():
        _randomize_bn_stats_impl(model, gen)


def _randomize_bn_stats_impl(model, gen):
    for m in model.modules():
        if isinstance(m, tnn.BatchNorm2d):
            m.weight.copy_(
                1.0 + torch.randn(m.weight.shape, generator=gen) * 0.1)
            m.bias.copy_(torch.randn(m.bias.shape, generator=gen) * 0.1)
            m.running_mean.copy_(
                torch.randn(m.running_mean.shape, generator=gen) * 0.1)
            m.running_var.copy_(
                torch.rand(m.running_var.shape, generator=gen) + 0.5)


def test_resnet50_import_forward_parity():
    gen = torch.Generator().manual_seed(0)
    with torch.no_grad():
        net = TorchResNet50(num_classes=10)
        for p in net.parameters():
            p.copy_(torch.randn(p.shape, generator=gen) * 0.05)
        _randomize_bn_stats(net, gen)
        net.eval()
        x = torch.randn(2, 3, 64, 64, generator=gen)
        ref = net(x).numpy()

    variables = import_torch_resnet(net.state_dict(), "resnet50")
    model = ResNet50(num_classes=10)
    out = model.apply(
        {"params": variables["params"],
         "batch_stats": variables["batch_stats"]},
        jnp.asarray(x.numpy().transpose(0, 2, 3, 1)), train=False)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=1e-3)


@pytest.mark.slow
def test_merge_pretrained_without_head():
    """Fine-tune path: import the backbone, keep a fresh 5-way head."""
    gen = torch.Generator().manual_seed(1)
    with torch.no_grad():
        net = TorchResNet50(num_classes=10)
        for p in net.parameters():
            p.copy_(torch.randn(p.shape, generator=gen) * 0.05)
        net.eval()
    imported = import_torch_resnet(net.state_dict(), "resnet50",
                                   include_fc=False)
    model = ResNet50(num_classes=5)
    fresh = model.init({"params": jax.random.PRNGKey(0)},
                       jnp.zeros((1, 64, 64, 3)), train=False)
    merged = merge_pretrained(dict(fresh), imported)
    # backbone overlaid, head untouched
    np.testing.assert_allclose(
        merged["params"]["Conv_0"]["kernel"],
        net.state_dict()["conv1.weight"].numpy().transpose(2, 3, 1, 0))
    assert merged["params"]["Dense_0"]["kernel"].shape == (2048, 5)
    # merged variables actually run
    out = model.apply(merged, jnp.zeros((1, 64, 64, 3)), train=False)
    assert out.shape == (1, 5)


@pytest.mark.slow
def test_eval_pretrained_harness(tmp_path, capsys):
    """The import→eval harness (docs/ACCURACY.md): `infer eval
    --pretrained x.pth` must run a full evaluation from a torch-format
    checkpoint with no workdir checkpoint — the command a user points at
    real ImageNet val to verify the published numbers."""
    from deep_vision_tpu.cli import infer
    from deep_vision_tpu.core.config import get_config

    gen = torch.Generator().manual_seed(3)
    with torch.no_grad():
        net = TorchResNet50(num_classes=get_config("resnet50").num_classes)
        for p in net.parameters():
            p.copy_(torch.randn(p.shape, generator=gen) * 0.05)
        net.eval()
    pth = tmp_path / "w.pth"
    torch.save(net.state_dict(), pth)

    infer.main(["eval", "-m", "resnet50", "--workdir", str(tmp_path / "w"),
                "--pretrained", str(pth), "--synthetic",
                "--synthetic-size", "8", "--batch-size", "8"])
    out = capsys.readouterr().out
    assert "imported resnet50 weights" in out
    assert "top1=" in out and "eval[" in out


def test_import_rejects_wrong_shape():
    with torch.no_grad():
        net = TorchResNet50(num_classes=10)
    imported = import_torch_resnet(net.state_dict(), "resnet50")
    # a freshly-initialized model with a 7-class head (vs the checkpoint's
    # 10): same tree, different Dense_0 shapes — no flax init needed, the
    # mismatch check is pure tree/shape validation
    fresh = jax.tree_util.tree_map(np.asarray, imported)
    fresh["params"]["Dense_0"] = {
        "kernel": np.zeros((2048, 7), np.float32),
        "bias": np.zeros((7,), np.float32)}
    with pytest.raises(ValueError, match="shape mismatch"):
        merge_pretrained(fresh, imported)
