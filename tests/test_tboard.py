"""The dependency-free tfevents writer must produce files a STOCK
TensorBoard reads back exactly — verified with tensorboard's own
EventFileLoader (the consuming side of the reference's tf.summary.scalar
logging, YOLO/tensorflow/train.py:159-179)."""

import glob

import numpy as np
import pytest

from deep_vision_tpu.core.metrics import MetricLogger
from deep_vision_tpu.core.tboard import TFEventWriter, _crc32c


def _scalar(v) -> float:
    """TB >= 2.x data-compat rewrites simple_value into a DT_FLOAT tensor."""
    if v.HasField("tensor") and v.tensor.float_val:
        return float(v.tensor.float_val[0])
    return float(v.simple_value)


def test_crc32c_known_vectors():
    # RFC 3720 test vectors
    assert _crc32c(b"") == 0x0
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(bytes(32)) == 0x8A9136AA


def test_roundtrip_via_tensorboard_reader(tmp_path):
    loader_mod = pytest.importorskip(
        "tensorboard.backend.event_processing.event_file_loader")

    w = TFEventWriter(str(tmp_path))
    w.scalar("train_loss", 1.5, step=1)
    w.scalar("train_loss", 0.75, step=2)
    w.scalars({"val_top1": 0.9, "val_top5": 0.99}, step=2)
    w.close()

    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    events = list(loader_mod.EventFileLoader(path).Load())
    seen = []
    for e in events:
        for v in e.summary.value:
            seen.append((v.tag, e.step, round(_scalar(v), 4)))
    assert ("train_loss", 1, 1.5) in seen
    assert ("train_loss", 2, 0.75) in seen
    assert ("val_top1", 2, 0.9) in seen
    assert ("val_top5", 2, 0.99) in seen


def test_metric_logger_emits_tensorboard(tmp_path):
    pytest.importorskip(
        "tensorboard.backend.event_processing.event_file_loader")
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader,
    )

    logger = MetricLogger(str(tmp_path))
    logger.log("loss", 10, 3.25)
    logger.log_dict(20, {"top1": 0.5})
    (path,) = glob.glob(str(tmp_path / "tensorboard" / "events.*"))
    tags = {(v.tag, e.step): _scalar(v)
            for e in EventFileLoader(path).Load()
            for v in e.summary.value}
    assert tags[("loss", 10)] == pytest.approx(3.25)
    assert tags[("top1", 20)] == pytest.approx(0.5)
    # JSONL mirror still written
    assert (tmp_path / "metrics.jsonl").exists()


def test_metric_logger_tensorboard_off(tmp_path):
    logger = MetricLogger(str(tmp_path), tensorboard=False)
    logger.log("loss", 1, 1.0)
    assert not glob.glob(str(tmp_path / "tensorboard" / "*"))
