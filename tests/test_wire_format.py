"""Uint8 serving wire + bf16 inference parity suite (CPU, tier-1 fast).

The wire-format contract (docs/SERVING.md "Wire format & inference
dtype"): a uint8-wire engine stages and H2D-transfers raw 0–255 pixels
— 4× fewer bytes per padded batch than float32, asserted here via the
``h2d_bytes`` stat — while the bucket program's traced prologue applies
the SAME normalization math the float32-wire client runs on the host,
so outputs stay allclose (classification top-1 bit-identical) on every
execution mode: single engine at pipeline depths 1/2, ReplicatedEngine
over forced host devices, and the --shard-batches mesh path.  bf16
compute keeps float32 outputs within loose tolerance with the same
top-1.

Uses LeNet at random init (restore's no-checkpoint fallback): wire
parity is about the dtype plumbing, not learned weights."""

import json
import urllib.error
import urllib.request
from concurrent.futures import wait

import numpy as np
import pytest

from deep_vision_tpu.serve.engine import (
    BatchingEngine,
    StagingPool,
    sharded_buckets,
)
from deep_vision_tpu.serve.registry import ModelRegistry

pytestmark = pytest.mark.serve

MNIST_MEAN, MNIST_STD = 0.1307, 0.3081


@pytest.fixture(scope="module")
def wire_serving(tmp_path_factory):
    """One restore, three wire/compute views of the same weights."""
    reg = ModelRegistry()
    td = str(tmp_path_factory.mktemp("wire_workdir"))
    sm_f32 = reg.load_checkpoint("lenet5", td, name="lenet_f32")
    sm_u8 = reg.load_checkpoint("lenet5", td, name="lenet_u8",
                                wire_dtype="uint8")
    sm_bf16 = reg.load_checkpoint("lenet5", td, name="lenet_bf16",
                                  wire_dtype="uint8",
                                  infer_dtype="bfloat16")
    return sm_f32, sm_u8, sm_bf16


def _raw_images(n, shape=(32, 32, 1)):
    return [np.random.RandomState(i).randint(0, 256, shape, dtype=np.uint8)
            for i in range(n)]


def _host_normalized(raw):
    """The float32-wire client's host path (data/mnist.py math)."""
    return [((r.astype(np.float32) / 255.0) - MNIST_MEAN) / MNIST_STD
            for r in raw]


def _serve_all(engine, images, timeout=120):
    futs = [engine.submit(x) for x in images]
    wait(futs, timeout)
    return [np.asarray(f.result(0)) for f in futs]


def _assert_parity(ref, got, atol=1e-5):
    for a, b in zip(ref, got):
        np.testing.assert_allclose(a, b, atol=atol, rtol=1e-5)
        assert int(np.argmax(a)) == int(np.argmax(b))


# -- device-side normalization math --------------------------------------


def test_serve_normalize_matches_host_math():
    """Each normalization family's device prologue is the host path's
    math exactly (same op order) — checked per family without paying a
    model compile."""
    import jax.numpy as jnp

    from deep_vision_tpu.data.transforms import normalize
    from deep_vision_tpu.ops.preprocess import serve_normalize

    rgb = np.random.RandomState(0).randint(0, 256, (2, 8, 8, 3),
                                           dtype=np.uint8)
    got = np.asarray(serve_normalize(jnp.asarray(rgb), "imagenet"))
    want = np.stack([normalize(r) for r in rgb])
    np.testing.assert_allclose(got, want, atol=1e-6)

    gray = np.random.RandomState(1).randint(0, 256, (2, 8, 8, 1),
                                            dtype=np.uint8)
    got = np.asarray(serve_normalize(jnp.asarray(gray), "mnist"))
    want = ((gray.astype(np.float32) / 255.0) - MNIST_MEAN) / MNIST_STD
    np.testing.assert_allclose(got, want, atol=1e-6)

    got = np.asarray(serve_normalize(jnp.asarray(rgb), "unit"))
    np.testing.assert_allclose(got, rgb.astype(np.float32) / 255.0,
                               atol=1e-6)

    with pytest.raises(ValueError, match="unknown serve preprocess"):
        serve_normalize(jnp.asarray(rgb), "nope")


def test_serve_preprocess_kind_derivation():
    from deep_vision_tpu.ops.preprocess import serve_preprocess_kind

    assert serve_preprocess_kind("classification", 3) == "imagenet"
    assert serve_preprocess_kind("classification", 1) == "mnist"
    assert serve_preprocess_kind("detection", 3) == "unit"
    assert serve_preprocess_kind("pose", 3) == "unit"


def test_registry_dtype_validation_and_describe(wire_serving):
    sm_f32, sm_u8, sm_bf16 = wire_serving
    assert sm_f32.describe()["wire_dtype"] == "float32"
    d = sm_bf16.describe()
    assert d["wire_dtype"] == "uint8" and d["infer_dtype"] == "bfloat16"
    assert sm_u8.preprocess_kind == "mnist"
    reg = ModelRegistry()
    with pytest.raises(ValueError, match="wire_dtype"):
        reg.load_checkpoint("lenet5", "/nonexistent", wire_dtype="int8")
    with pytest.raises(ValueError, match="infer_dtype"):
        reg.load_checkpoint("lenet5", "/nonexistent",
                            infer_dtype="float16")


# -- single-engine parity + the 4x H2D win --------------------------------


@pytest.mark.parametrize("depth", [1, 2])
def test_uint8_wire_parity_across_buckets(wire_serving, depth):
    """Uint8-wire outputs allclose to the float32 path (top-1
    identical) with cohorts landing in BOTH buckets, at the synchronous
    and the pipelined depth."""
    sm_f32, sm_u8, _ = wire_serving
    raw = _raw_images(12)
    kw = dict(buckets=[4, 8], max_wait_ms=150, pipeline_depth=depth,
              watchdog_interval_s=0)
    with BatchingEngine(sm_f32, **kw) as eng:
        ref = _serve_all(eng, _host_normalized(raw[:8]))
        ref += _serve_all(eng, _host_normalized(raw[8:]))  # 4-bucket
    with BatchingEngine(sm_u8, **kw) as eng:
        got = _serve_all(eng, raw[:8])
        got += _serve_all(eng, raw[8:])
        assert sorted(eng.stats()["compiled_buckets"]) == [4, 8]
    _assert_parity(ref, got)


def test_h2d_bytes_drop_4x(wire_serving):
    """Acceptance: staged H2D bytes per padded batch drop exactly 4× on
    the uint8 wire — the same request stream through both wires forms
    the same padded buckets, so total and per-bucket bytes divide by
    the dtype width."""
    sm_f32, sm_u8, _ = wire_serving
    raw = _raw_images(8)
    stats = {}
    for key, sm, imgs in (("f32", sm_f32, _host_normalized(raw)),
                          ("u8", sm_u8, raw)):
        with BatchingEngine(sm, buckets=[8], max_wait_ms=250,
                            watchdog_interval_s=0) as eng:
            _serve_all(eng, imgs)
            stats[key] = eng.stats()
    f32, u8 = stats["f32"]["pipeline"], stats["u8"]["pipeline"]
    assert u8["h2d_transfers"] == f32["h2d_transfers"] == 1
    assert u8["h2d_bytes"] == 8 * 32 * 32 * 1          # uint8 batch
    assert f32["h2d_bytes"] == 4 * u8["h2d_bytes"]     # the 4x win
    assert f32["h2d_bytes_by_bucket"][8] \
        == 4 * u8["h2d_bytes_by_bucket"][8]
    assert stats["u8"]["wire_dtype"] == "uint8"
    assert stats["f32"]["wire_dtype"] == "float32"


def test_staging_pool_dtype_reuse():
    """Pooled staging buffers allocate in the wire dtype and are reused
    across acquire/release cycles — no per-batch reallocation and no
    float32 fallback on the uint8 wire."""
    pool = StagingPool((32, 32, 1), np.uint8)
    a = pool.acquire(8)
    assert a.dtype == np.uint8 and a.shape == (8, 32, 32, 1)
    pool.release(8, a)
    b = pool.acquire(8)
    assert b is a  # the SAME buffer came back
    assert pool.allocated == 1 and pool.reused == 1
    assert pool.stats()["dtype"] == "uint8"
    # default stays float32 for wire-f32 engines
    assert StagingPool((32, 32, 1)).acquire(2).dtype == np.float32


def test_bf16_compute_tolerance(wire_serving):
    """bf16 bucket programs return FLOAT32 outputs within loose
    tolerance of the f32 path, top-1 intact (docs/SERVING.md bf16
    caveats)."""
    sm_f32, _, sm_bf16 = wire_serving
    raw = _raw_images(8)
    kw = dict(buckets=[8], max_wait_ms=250, watchdog_interval_s=0)
    with BatchingEngine(sm_f32, **kw) as eng:
        ref = _serve_all(eng, _host_normalized(raw))
    with BatchingEngine(sm_bf16, **kw) as eng:
        got = _serve_all(eng, raw)
        assert eng.stats()["infer_dtype"] == "bfloat16"
    for a, b in zip(ref, got):
        assert b.dtype == np.float32
        np.testing.assert_allclose(a, b, atol=5e-2, rtol=5e-2)
        assert int(np.argmax(a)) == int(np.argmax(b))


# -- multi-device execution modes -----------------------------------------


def test_replicated_uint8_parity(wire_serving, host_devices):
    """ReplicatedEngine on forced host devices serves the uint8 wire
    allclose to the single-engine float32 reference (per-replica views
    inherit the wire dtype through for_device)."""
    from deep_vision_tpu.serve.replicas import ReplicatedEngine

    sm_f32, sm_u8, _ = wire_serving
    raw = _raw_images(16)
    with BatchingEngine(sm_f32, max_batch=8, max_wait_ms=150,
                        watchdog_interval_s=0) as eng:
        ref = _serve_all(eng, _host_normalized(raw))
    with ReplicatedEngine(sm_u8, devices=host_devices[:2], max_batch=8,
                          max_wait_ms=150) as eng:
        got = _serve_all(eng, raw)
        st = eng.stats()
    assert st["wire_dtype"] == "uint8"
    assert st["pipeline"]["h2d_transfers"] >= 1
    assert st["pipeline"]["h2d_bytes"] \
        == sum(st["pipeline"]["h2d_bytes_by_bucket"].values())
    _assert_parity(ref, got)


def test_shard_batches_uint8_parity(wire_serving, host_devices):
    """The --shard-batches mesh path on the uint8 wire: mega-batches
    laid across a 2-device data axis match the float32 reference."""
    from deep_vision_tpu.parallel.mesh import make_mesh

    sm_f32, sm_u8, _ = wire_serving
    raw = _raw_images(8)
    with BatchingEngine(sm_f32, max_batch=8, max_wait_ms=250,
                        watchdog_interval_s=0) as eng:
        ref = _serve_all(eng, _host_normalized(raw))
    mesh = make_mesh({"data": 2}, devices=host_devices[:2])
    buckets = sharded_buckets(8, 2)
    with BatchingEngine(sm_u8.for_mesh(mesh), buckets=buckets,
                        max_wait_ms=250, watchdog_interval_s=0) as eng:
        got = _serve_all(eng, raw)
        st = eng.stats()
    assert st["wire_dtype"] == "uint8"
    _assert_parity(ref, got)


def test_bf16_sharded_and_replicated_run(wire_serving, host_devices):
    """bf16 + uint8 wire works on both multi-device modes (the
    all-three-execution-modes acceptance for the infer-dtype knob)."""
    from deep_vision_tpu.parallel.mesh import make_mesh
    from deep_vision_tpu.serve.replicas import ReplicatedEngine

    _, _, sm_bf16 = wire_serving
    raw = _raw_images(4)
    with ReplicatedEngine(sm_bf16, devices=host_devices[:2],
                          max_batch=4, max_wait_ms=100) as eng:
        rows = _serve_all(eng, raw)
    assert all(r.dtype == np.float32 for r in rows)
    mesh = make_mesh({"data": 2}, devices=host_devices[:2])
    with BatchingEngine(sm_bf16.for_mesh(mesh),
                        buckets=sharded_buckets(4, 2), max_wait_ms=100,
                        watchdog_interval_s=0) as eng:
        rows2 = _serve_all(eng, raw)
    for a, b in zip(rows, rows2):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


# -- HTTP wire contract ----------------------------------------------------


def _post(base, route, payload):
    req = urllib.request.Request(
        base + route, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read())


def test_http_uint8_wire_and_nonfinite_rejection(wire_serving):
    """Clients POST raw integer pixels on the uint8 wire; NaN/Inf
    payloads answer 400 on BOTH wires instead of reaching the batcher
    (float64 detour gone: lists decode straight to the wire dtype)."""
    from deep_vision_tpu.serve.http import ServeServer

    sm_f32, sm_u8, _ = wire_serving
    reg = ModelRegistry()
    reg.add(sm_u8)
    reg.add(sm_f32)
    engines = {
        sm.name: BatchingEngine(sm, max_batch=4, max_wait_ms=2.0,
                                watchdog_interval_s=0).start()
        for sm in (sm_u8, sm_f32)}
    srv = ServeServer(reg, engines, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        raw = _raw_images(1)[0]
        status, out = _post(base, "/v1/classify",
                            {"pixels": raw[..., 0].tolist(),
                             "model": sm_u8.name})
        assert status == 200 and len(out["top"]) == 5
        # same pixels through the f32 wire (host-normalized): top-1 match
        _, out_f = _post(
            base, "/v1/classify",
            {"pixels": _host_normalized([raw])[0][..., 0].tolist(),
             "model": sm_f32.name})
        assert out["top"][0]["class"] == out_f["top"][0]["class"]
        bad = np.zeros((32, 32), np.float64)
        bad[0, 0] = np.nan
        for model in (sm_u8.name, sm_f32.name):
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(base, "/v1/classify",
                      {"pixels": bad.tolist(), "model": model})
            assert exc.value.code == 400
        # ragged payloads are a 400, not a 500
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base, "/v1/classify",
                  {"pixels": [[1, 2], [3]], "model": sm_u8.name})
        assert exc.value.code == 400
    finally:
        srv.shutdown()
        for eng in engines.values():
            eng.stop()
