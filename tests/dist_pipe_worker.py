"""Worker for test_distributed_pipeline_fit: one rank of a 2-process CPU
'pod' with 2 local virtual devices each, training the stacked hourglass
through the PIPELINED model on a {data:2, pipe:2} mesh laid out the way a
real deep-stack pod run would be — ``data`` ACROSS processes (DCN), ``pipe``
WITHIN each process (ICI).  Exercises the composition the single-process
pipeline tests can't: stage-sharded state placement + Orbax save/restore
under jax.process_count() > 1, per-rank data shards feeding a data×pipe
mesh, and a fresh-trainer resume (VERDICT r4 weak #3).

Run: python dist_pipe_worker.py <coordinator> <process_id> <n> <workdir>.
"""

import os
import sys

# 2 virtual CPU devices per process, BEFORE any jax import
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if not f.startswith("--xla_force_host_platform_device_count")]
flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize pins the TPU

import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from deep_vision_tpu.parallel.distributed import initialize  # noqa: E402

HEAT = 3


def _pod_pipe_mesh(nprocs: int) -> Mesh:
    """{data: nprocs, pipe: local} with data rows == processes, so the
    pipeline's ppermute ring stays process-local (ICI) and only the
    gradient psum crosses the process boundary (DCN)."""
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    grid = np.array(devs).reshape(nprocs, len(devs) // nprocs)
    for row in grid:
        assert len({d.process_index for d in row}) == 1, grid
    return Mesh(grid, ("data", "pipe"))


def main():
    coordinator, pid, nprocs, workdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    initialize(coordinator_address=coordinator, num_processes=nprocs,
               process_id=pid)
    mesh = _pod_pipe_mesh(nprocs)

    import jax.numpy as jnp

    from deep_vision_tpu.core.config import OptimizerConfig, TrainConfig
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.pose import PoseLoader, synthetic_pose_dataset
    from deep_vision_tpu.models.hourglass import StackedHourglass
    from deep_vision_tpu.parallel.pipelined import PipelinedModel
    from deep_vision_tpu.tasks.pose import PoseTask

    def model_fn():
        return StackedHourglass(num_stack=2, num_heatmap=HEAT, filters=8,
                                order=1, dtype=jnp.float32)

    def cfg_for(epochs):
        return TrainConfig(
            name="hg_dist_pipe", model=model_fn, task="pose",
            batch_size=8, total_epochs=epochs,
            optimizer=OptimizerConfig(name="sgd", learning_rate=1e-3),
            image_size=32, num_classes=HEAT, half_precision=False,
            log_every_steps=1)

    # identical seeded dataset on every rank; each rank FEEDS its own
    # interleaved shard — global batch 8 = 4 local × 2 processes
    samples = synthetic_pose_dataset(16, 32, HEAT, seed=5)
    shard = [samples[i] for i in range(pid, len(samples), nprocs)]

    def loaders():
        return (PoseLoader(shard, 4, 32, 8, HEAT, train=True, seed=1),
                PoseLoader(shard, 4, 32, 8, HEAT, train=False))

    cfg = cfg_for(2)
    pm = PipelinedModel.for_model(model_fn(), mesh, num_microbatches=2)
    trainer = Trainer(cfg, pm, PoseTask(), mesh=mesh, workdir=workdir)
    train_loader, val_loader = loaders()
    state = trainer.fit(train_loader, val_loader)
    step1 = int(jax.device_get(state.step))
    m1 = trainer.evaluate(state, val_loader)
    assert np.isfinite(m1["loss"]), m1
    assert trainer.checkpointer.latest_step() == step1
    # the stage-stacked params really are sharded over the local pipe axis
    leaf = jax.tree_util.tree_leaves(state.params["stages"])[0]
    assert leaf.sharding.spec[0] == "pipe", leaf.sharding
    print(f"FIT pid={pid} step={step1} loss={m1['loss']:.6f}", flush=True)

    # resume on a FRESH trainer from the shared checkpoint dir, train one
    # more epoch — the pod-recovery path for a pipeline-sharded run
    cfg2 = cfg_for(3)
    pm2 = PipelinedModel.for_model(model_fn(), mesh, num_microbatches=2)
    trainer2 = Trainer(cfg2, pm2, PoseTask(), mesh=mesh, workdir=workdir)
    train2, val2 = loaders()
    state2 = trainer2.fit(train2, val2, resume=True)
    step2 = int(jax.device_get(state2.step))
    assert trainer2.start_epoch == 3, trainer2.start_epoch
    assert step2 > step1, (step1, step2)
    leaf2 = jax.tree_util.tree_leaves(state2.params["stages"])[0]
    assert leaf2.sharding.spec[0] == "pipe", leaf2.sharding
    m2 = trainer2.evaluate(state2, val2)
    print(f"RESULT pid={pid} step={step2} loss={m2['loss']:.6f}", flush=True)


if __name__ == "__main__":
    main()
