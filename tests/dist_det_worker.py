"""Worker for test_distributed_detection_fit: one rank of a 2-process CPU
'pod' training YOLO-toy data-parallel with PER-RANK detection data shards
— the multi-host detection case VERDICT r4 weak #3 called out: sharded
record reads feed a process-spanning {data:4} mesh, the 3-scale label
encode runs host-side per rank, and the mAP host-evaluator gathers every
rank's decoded detections so all ranks report the same global metrics.

Run: python dist_det_worker.py <coordinator> <process_id> <n> <workdir>.
"""

import os
import sys

# 2 virtual CPU devices per process, BEFORE any jax import
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if not f.startswith("--xla_force_host_platform_device_count")]
flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize pins the TPU

import numpy as np  # noqa: E402

from deep_vision_tpu.parallel.distributed import (  # noqa: E402
    initialize,
    make_pod_mesh,
)


def main():
    coordinator, pid, nprocs, workdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    initialize(coordinator_address=coordinator, num_processes=nprocs,
               process_id=pid)
    mesh = make_pod_mesh({"data": -1})

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.detection import (
        DetectionLoader,
        synthetic_detection_dataset,
    )
    from deep_vision_tpu.tasks.detection import YoloTask

    cfg = get_config("yolov3_toy")
    cfg.total_epochs = 2
    cfg.log_every_steps = 2

    # identical seeded dataset on every rank; each rank FEEDS its own
    # interleaved shard (per-host record reads) — global batch 8 = 4×2
    samples = synthetic_detection_dataset(16, 64, 3, seed=3)
    shard = [samples[i] for i in range(pid, len(samples), nprocs)]
    train = DetectionLoader(shard, 4, 3, 64, train=True, augment=False,
                            seed=1)
    val = DetectionLoader(shard, 4, 3, 64, train=False)

    trainer = Trainer(cfg, cfg.model(), YoloTask(3), mesh=mesh,
                      workdir=workdir)
    state = trainer.fit(train, val)
    step = int(jax.device_get(state.step))
    m = trainer.evaluate(state, val)
    assert np.isfinite(m["loss"]), m
    # the host mAP accumulator ran over the GLOBAL (allgathered) val set
    assert "mAP" in m and "mAP50_95" in m, m
    print(f"RESULT pid={pid} step={step} loss={m['loss']:.6f} "
          f"mAP={m['mAP']:.4f} mAP50_95={m['mAP50_95']:.4f}", flush=True)


if __name__ == "__main__":
    main()
