"""ImageNet pipeline tests on synthetic JPEGs (no dataset download)."""

import os

import numpy as np
import pytest

from deep_vision_tpu.data import transforms as T
from deep_vision_tpu.data.imagenet import ImageNetFolder, ImageNetLoader

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


@pytest.fixture(scope="module")
def fake_imagenet(tmp_path_factory):
    root = tmp_path_factory.mktemp("imagenet")
    img_dir = root / "train"
    img_dir.mkdir()
    synsets = ["n01440764", "n01443537", "n01484850"]
    rng = np.random.default_rng(0)
    for s_i, syn in enumerate(synsets):
        for j in range(6):
            arr = rng.integers(0, 255, size=(40 + 8 * s_i, 64, 3),
                               dtype=np.uint8)
            Image.fromarray(arr).save(img_dir / f"{syn}_{j}.JPEG")
    labels_file = root / "metadata.txt"
    labels_file.write_text(
        "\n".join(f"{s} class_{i}" for i, s in enumerate(synsets)))
    return str(img_dir), str(labels_file)


def test_folder_labels_from_filename_prefix(fake_imagenet):
    root, labels = fake_imagenet
    ds = ImageNetFolder(root, labels)
    assert len(ds) == 18
    img, label = ds.read(0)
    assert img.ndim == 3 and img.shape[2] == 3
    assert 0 <= label < 3


def test_transforms_shapes_and_ranges():
    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, size=(300, 400, 3), dtype=np.uint8)
    out = T.train_transform(img, rng, size=224, resize=256)
    assert out.shape == (224, 224, 3) and out.dtype == np.float32
    ev = T.eval_transform(img, size=224, resize=256)
    assert ev.shape == (224, 224, 3)
    # rescale puts the SHORTER side at the target
    r = T.rescale(img, 256)
    assert min(r.shape[:2]) == 256 and max(r.shape[:2]) == 341


def test_rescale_no_op_and_portrait():
    img = np.zeros((500, 250, 3), np.uint8)
    r = T.rescale(img, 100)
    assert r.shape == (200, 100, 3)


def test_center_crop_is_deterministic():
    img = np.arange(10 * 10 * 3, dtype=np.uint8).reshape(10, 10, 3)
    a = T.center_crop(img, 4)
    b = T.center_crop(img, 4)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (4, 4, 3)


def test_loader_batches_and_reshuffle(fake_imagenet):
    root, labels = fake_imagenet
    loader = ImageNetLoader(root, labels, batch_size=4, train=True,
                            image_size=32, resize=36, num_workers=0,
                            process_index=0, process_count=1)
    batches = list(loader)
    assert len(batches) == 4  # 18 // 4
    b = batches[0]
    assert b["image"].shape == (4, 32, 32, 3)
    assert b["label"].dtype == np.int32
    loader.set_epoch(1)
    batches2 = list(loader)
    # different epoch ⇒ different order (labels differ somewhere)
    l1 = np.concatenate([b["label"] for b in batches])
    l2 = np.concatenate([b["label"] for b in batches2])
    assert not np.array_equal(l1, l2)


def test_loader_host_sharding(fake_imagenet):
    root, labels = fake_imagenet
    l0 = ImageNetLoader(root, labels, batch_size=2, train=False,
                        image_size=32, resize=36, num_workers=0,
                        process_index=0, process_count=2)
    l1 = ImageNetLoader(root, labels, batch_size=2, train=False,
                        image_size=32, resize=36, num_workers=0,
                        process_index=1, process_count=2)
    assert len(set(l0.host_indices) & set(l1.host_indices)) == 0
    assert len(l0.host_indices) + len(l1.host_indices) == 18


def test_multiprocess_workers(fake_imagenet):
    root, labels = fake_imagenet
    loader = ImageNetLoader(root, labels, batch_size=4, train=True,
                            image_size=32, resize=36, num_workers=2,
                            process_index=0, process_count=1)
    try:
        b = next(iter(loader))
        assert b["image"].shape == (4, 32, 32, 3)
        assert np.isfinite(b["image"]).all()
    finally:
        loader.close()


def test_device_normalize_path_matches_host(fake_imagenet):
    """uint8 loader + device jitter_normalize(train=False) must reproduce
    the host eval_transform exactly (same crop, same normalization)."""
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.ops.preprocess import jitter_normalize

    root, labels = fake_imagenet
    host = ImageNetLoader(root, labels, batch_size=4, train=False,
                          image_size=32, resize=36, num_workers=0,
                          process_index=0, process_count=1)
    dev = ImageNetLoader(root, labels, batch_size=4, train=False,
                         image_size=32, resize=36, num_workers=0,
                         process_index=0, process_count=1,
                         device_normalize=True)
    hb = next(iter(host))
    db = next(iter(dev))
    assert db["image"].dtype == np.uint8
    out = np.asarray(jitter_normalize(jnp.asarray(db["image"]),
                                      jax.random.PRNGKey(0), train=False))
    np.testing.assert_allclose(out, hb["image"], atol=1e-5)


@pytest.mark.slow
def test_device_preprocess_trains(fake_imagenet, tmp_path, mesh1):
    """End-to-end: uint8 batches through Trainer(preprocess_fn=...) —
    the fused-device path the ImageNet CLI uses by default."""
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.ops.preprocess import make_imagenet_preprocess
    from deep_vision_tpu.tasks.classification import ClassificationTask

    root, labels = fake_imagenet
    cfg = get_config("resnet50")
    cfg.total_epochs = 1
    cfg.batch_size = cfg.eval_batch_size = 4
    cfg.image_size = 32
    loader = ImageNetLoader(root, labels, batch_size=4, train=True,
                            image_size=32, resize=36, num_workers=0,
                            process_index=0, process_count=1,
                            device_normalize=True)
    trainer = Trainer(cfg, cfg.model(), ClassificationTask(cfg.num_classes),
                      mesh=mesh1, workdir=str(tmp_path),
                      preprocess_fn=make_imagenet_preprocess())
    state = trainer.fit(loader, None)
    assert int(np.asarray(state.step)) == len(loader)


def test_val_loader_isolated_from_train_with_zero_workers(fake_imagenet):
    """Regression: two 0-worker loaders must not share decode state —
    val must read val files with eval transforms."""
    root, labels = fake_imagenet
    tr = ImageNetLoader(root, labels, batch_size=4, train=True,
                        image_size=32, resize=36, num_workers=0,
                        process_index=0, process_count=1)
    va = ImageNetLoader(root, labels, batch_size=4, train=False,
                        image_size=32, resize=36, num_workers=0,
                        process_index=0, process_count=1)
    _ = next(iter(tr))  # train first, as fit() does
    b1 = next(iter(va))
    b2 = next(iter(va))
    # eval transform is deterministic ⇒ identical batches across epochs
    np.testing.assert_array_equal(b1["image"], b2["image"])
    np.testing.assert_array_equal(b1["label"], b2["label"])


def test_eval_pads_final_partial_batch(fake_imagenet):
    root, labels = fake_imagenet
    va = ImageNetLoader(root, labels, batch_size=4, train=False,
                        image_size=32, resize=36, num_workers=0,
                        process_index=0, process_count=1)
    batches = list(va)
    assert len(batches) == 5  # 18 imgs → 4 full + 1 padded
    w = np.concatenate([b["weight"] for b in batches])
    assert w.sum() == 18.0  # every real image counted exactly once
    assert batches[-1]["image"].shape == (4, 32, 32, 3)  # static shape


def test_prefetch_propagates_producer_errors():
    import jax
    import pytest as _pytest

    from deep_vision_tpu.data.loader import prefetch_to_device
    from deep_vision_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])

    def bad_iter():
        yield {"image": np.zeros((2, 4, 4, 1), np.float32)}
        raise RuntimeError("decode failed")

    it = prefetch_to_device(bad_iter(), mesh)
    next(it)
    with _pytest.raises(RuntimeError, match="decode failed"):
        next(it)


def test_tf_preprocessing_semantics():
    """TF 'ResNet preprocessing' variant (ResNet/tensorflow/data_load.py):
    aspect-preserving resize, central crop, and mean subtraction in RAW
    0-255 space with NO std scaling."""
    rng = np.random.default_rng(3)
    img = rng.integers(0, 255, size=(100, 200, 3), dtype=np.uint8)
    out = T.tf_eval_transform(img, size=64, resize=96)
    assert out.shape == (64, 64, 3) and out.dtype == np.float32
    # exact mean subtraction: central crop of the resized image minus means
    resized = T.rescale(img, 96)
    assert resized.shape[0] == 96  # smaller side pinned, aspect kept
    assert resized.shape[1] == 192
    expect = T.center_crop(resized, 64).astype(np.float32) - T.TF_CHANNEL_MEANS
    np.testing.assert_allclose(out, expect, atol=1e-5)
    # train path: right shape/range, varies with rng
    a = T.tf_train_transform(img, np.random.default_rng(0), 64, 96)
    b = T.tf_train_transform(img, np.random.default_rng(7), 64, 96)
    assert a.shape == (64, 64, 3)
    assert a.min() >= -T.TF_CHANNEL_MEANS.max() - 1e-3
    assert a.max() <= 255.0
    assert not np.allclose(a, b)


def test_loader_tf_preprocessing(fake_imagenet):
    root, labels = fake_imagenet
    loader = ImageNetLoader(root, labels, batch_size=4, train=False,
                            image_size=32, resize=40, num_workers=0,
                            process_index=0, process_count=1,
                            preprocessing="tf")
    batch = next(iter(loader))
    x = batch["image"]
    assert x.shape == (4, 32, 32, 3) and x.dtype == np.float32
    # mean-centered raw-range values, NOT [0,1]-normalized
    assert x.min() < -50 and x.max() > 50
    with pytest.raises(ValueError, match="host-side only"):
        ImageNetLoader(root, labels, 4, num_workers=0, process_index=0,
                       process_count=1, preprocessing="tf",
                       device_normalize=True)


def test_record_loader_matches_folder(fake_imagenet, tmp_path):
    """The dvrec consumption path (reference TFRecord trainer role,
    ResNet/tensorflow/train.py:178-214): shards built by prepare_imagenet
    feed the same loader and yield byte-identical eval batches to the
    folder path."""
    from deep_vision_tpu.data import prep

    root, labels = fake_imagenet
    out = str(tmp_path / "recs")
    n = prep.prepare_imagenet(root, labels, out, "val", num_shards=3,
                              num_workers=1)
    assert n == 18
    kwargs = dict(train=False, image_size=32, resize=40, num_workers=0,
                  process_index=0, process_count=1)
    folder = ImageNetLoader(root, labels, batch_size=6, **kwargs)
    records = ImageNetLoader.from_records(out, "val", batch_size=6, **kwargs)
    assert len(records) == len(folder)
    # deterministic eval transform + same source images → same multiset of
    # (label, image-checksum) pairs across the epoch
    def sig(loader):
        out = []
        for b in loader:
            for img, lab in zip(b["image"], b["label"]):
                out.append((int(lab), float(np.abs(img).sum())))
        return sorted(out)
    np.testing.assert_allclose(np.asarray(sig(records)),
                               np.asarray(sig(folder)), rtol=1e-6)


def test_raw_record_loader_matches_folder(fake_imagenet, tmp_path):
    """`--store raw` shards (decode ONCE at build, store rescaled uint8 —
    the decode-free read path that feeds a chip from one host core) must
    yield the SAME eval batches as the decode-at-read folder path: both
    rescale the same decoded pixels with the same backend, just at
    different times."""
    from deep_vision_tpu.data import prep

    root, labels = fake_imagenet
    out = str(tmp_path / "recs_raw")
    n = prep.prepare_imagenet(root, labels, out, "val", num_shards=3,
                              num_workers=1, store="raw", resize=40)
    assert n == 18
    kwargs = dict(train=False, image_size=32, resize=40, num_workers=0,
                  process_index=0, process_count=1)
    folder = ImageNetLoader(root, labels, batch_size=6, **kwargs)
    raw = ImageNetLoader.from_records(out, "val", batch_size=6, **kwargs)
    assert len(raw) == len(folder)
    # shard fan-out interleaves items, so compare the epoch as a multiset
    # of (label, image-checksum) pairs — deterministic eval transform +
    # same decoded pixels ⇒ identical signatures
    def sig(loader):
        res = []
        for b in loader:
            for img, lab in zip(b["image"], b["label"]):
                res.append((int(lab), float(np.abs(img).sum())))
        return sorted(res)
    np.testing.assert_allclose(np.asarray(sig(raw)),
                               np.asarray(sig(folder)), rtol=1e-6)


def test_raw_record_loader_train_and_eval_len(fake_imagenet, tmp_path):
    from deep_vision_tpu.data import prep

    root, labels = fake_imagenet
    out = str(tmp_path / "recs_raw")
    prep.prepare_imagenet(root, labels, out, "train", num_shards=2,
                          num_workers=1, store="raw", resize=40)
    loader = ImageNetLoader.from_records(
        out, "train", batch_size=4, train=True, image_size=32, resize=40,
        num_workers=0, process_index=0, process_count=1,
        device_normalize=True)
    batches = list(loader)
    assert len(batches) == 18 // 4
    assert batches[0]["image"].shape == (4, 32, 32, 3)
    assert batches[0]["image"].dtype == np.uint8
    # eval: len() must count the padded partial batch it yields (ADVICE r2)
    ev = ImageNetLoader.from_records(
        out, "train", batch_size=4, train=False, image_size=32, resize=40,
        num_workers=0, process_index=0, process_count=1)
    assert len(ev) == len(list(ev)) == 5  # 18 → 4 full + 1 padded


def test_native_reader_matches_python_path(fake_imagenet, tmp_path,
                                           monkeypatch):
    """The C++ batch assembler (data/native/dvrec_reader.cc) must be
    BIT-EXACT with the Python read path — same per-item RNG draw order
    (flip, crop top, crop left), same crops, train and eval — so turning
    it on cannot change a training trajectory."""
    from deep_vision_tpu.data import native, prep

    if native.load() is None:
        pytest.skip("no C++ toolchain")
    root, labels = fake_imagenet
    out = str(tmp_path / "recs_raw")
    prep.prepare_imagenet(root, labels, out, "train", num_shards=2,
                          num_workers=1, store="raw", resize=40)

    def batches(train):
        loader = ImageNetLoader.from_records(
            out, "train", batch_size=4, train=train, image_size=32,
            resize=40, num_workers=0, process_index=0, process_count=1,
            device_normalize=True, seed=7)
        return list(loader)

    native_train = batches(True)
    native_eval = batches(False)
    assert any(b["image"].flags["C_CONTIGUOUS"] for b in native_train)
    # force the pure-Python path and compare byte-for-byte
    monkeypatch.setattr(
        "deep_vision_tpu.data.imagenet.ImageNetLoader._native_batch",
        lambda self, args, n_real: None)
    py_train = batches(True)
    py_eval = batches(False)
    assert len(native_train) == len(py_train) > 0
    for nb, pb in zip(native_train + native_eval, py_train + py_eval):
        np.testing.assert_array_equal(nb["label"], pb["label"])
        np.testing.assert_array_equal(nb["image"], pb["image"])
        if "weight" in pb:
            np.testing.assert_array_equal(nb["weight"], pb["weight"])


def test_record_loader_multiprocess(fake_imagenet, tmp_path):
    from deep_vision_tpu.data import prep

    root, labels = fake_imagenet
    out = str(tmp_path / "recs")
    prep.prepare_imagenet(root, labels, out, "train", num_shards=2,
                          num_workers=1)
    loader = ImageNetLoader.from_records(out, "train", batch_size=4,
                                         train=True, image_size=32,
                                         resize=40, num_workers=2,
                                         process_index=0, process_count=1)
    try:
        batches = list(loader)
        assert len(batches) == 18 // 4
        assert batches[0]["image"].shape == (4, 32, 32, 3)
        assert all(0 <= l < 3 for b in batches for l in b["label"])
    finally:
        loader.close()


def test_resize_backends_preserve_dtype():
    """resize_bilinear keeps dtype on BOTH backends; the PIL fallback must
    not truncate float images to uint8 (per-channel mode-F path)."""
    import deep_vision_tpu.data.transforms as T

    img_u8 = np.random.default_rng(0).integers(
        0, 255, (40, 60, 3), dtype=np.uint8)
    img_f = img_u8.astype(np.float32) / 255.0
    for backend_cv2 in (T._cv2, None):
        saved = T._cv2
        T._cv2 = backend_cv2
        try:
            out_u8 = T.resize_bilinear(img_u8, 30, 20)
            out_f = T.resize_bilinear(img_f, 30, 20)
        finally:
            T._cv2 = saved
        assert out_u8.shape == (20, 30, 3) and out_u8.dtype == np.uint8
        assert out_f.shape == (20, 30, 3) and out_f.dtype == np.float32
        # floats stay in range — a uint8 truncation would zero them out
        assert 0.2 < float(out_f.mean()) < 0.8
