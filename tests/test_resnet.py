"""Shape/param-count golden tests for the ResNet family (SURVEY §4a: the
reference's torchsummary printouts, ResNet/pytorch/train.py:350, are the spec;
param counts match torchvision's canonical models)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.models import resnet
from deep_vision_tpu.models.common import count_params


def _init(model, size=64):
    x = jnp.zeros((1, size, size, 3), jnp.float32)
    return jax.eval_shape(
        lambda a: model.init({"params": jax.random.PRNGKey(0)}, a,
                             train=False), x)


@pytest.mark.parametrize("ctor,expected", [
    (resnet.ResNet34, 21_797_672),
    (resnet.ResNet50, 25_557_032),
    (resnet.ResNet152, 60_192_808),
])
def test_param_counts(ctor, expected):
    variables = _init(ctor())
    assert count_params(variables["params"]) == expected


def test_resnet50v2_structure():
    variables = _init(resnet.ResNet50V2())
    n = count_params(variables["params"])
    # V2 reorganizes BN (pre-activation) but stays bottleneck-50-sized
    assert 25_000_000 < n < 26_000_000


def test_forward_shapes_and_dtype():
    model = resnet.ResNet50(num_classes=10, dtype=jnp.bfloat16)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32  # logits always f32


def test_train_mode_updates_batch_stats():
    model = resnet.ResNet34(num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, 3))
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    _, new_vars = model.apply(variables, x, train=True, mutable=["batch_stats"])
    old = jax.tree_util.tree_leaves(variables["batch_stats"])
    new = jax.tree_util.tree_leaves(new_vars["batch_stats"])
    assert any(not np.allclose(a, b) for a, b in zip(old, new))
