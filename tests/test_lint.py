"""dvtlint: per-rule fixture tests (both directions), the full-tree clean
run, and the runtime lock-order sanitizer's deliberate-inversion proof.

The fixtures under tests/fixtures/lint/ are tiny self-contained modules:
``dvtNNN_bad.py`` must trip exactly rule NNN, ``dvtNNN_good.py`` must come
back clean (its escape hatches counted as suppressed, not as findings).
"""

import threading

import pytest

import deep_vision_tpu
from deep_vision_tpu.analysis import RULE_CODES, run_paths
from deep_vision_tpu.analysis import sanitizer
from deep_vision_tpu.analysis.sanitizer import (
    LockOrderViolation, SanitizedLock, new_lock)

pytestmark = pytest.mark.lint

from pathlib import Path

FIXTURES = Path(__file__).parent / "fixtures" / "lint"
PACKAGE = Path(deep_vision_tpu.__file__).parent

# rule code -> number of distinct violations its bad fixture plants
EXPECTED_BAD = {
    "DVT001": 2,  # plain write + subscript store
    "DVT002": 2,  # call-edge cycle + annotated nested-with cycle
    "DVT003": 5,  # device_get, block_until_ready, asarray, item, float
    "DVT004": 4,  # time.*, np.random, print, attribute store
    "DVT005": 2,  # local t0 interval + self-attr interval
    "DVT006": 3,  # unannotated, bare, reasonless-noqa
    "DVT007": 5,  # queue get, event wait, thread join, 2 timeout-less dials
}


def run_fixture(name):
    path = FIXTURES / name
    assert path.exists(), path
    return run_paths([path], root=FIXTURES)


@pytest.mark.parametrize("code", RULE_CODES)
def test_bad_fixture_trips_exactly_its_rule(code):
    report = run_fixture(f"{code.lower()}_bad.py")
    assert report.findings, f"{code} bad fixture produced no findings"
    assert {f.code for f in report.findings} == {code}
    assert len(report.findings) == EXPECTED_BAD[code]
    for f in report.findings:
        assert f.line > 0 and f.path.endswith("_bad.py")


@pytest.mark.parametrize("code", RULE_CODES)
def test_good_fixture_is_clean(code):
    report = run_fixture(f"{code.lower()}_good.py")
    assert report.findings == [], [f.render() for f in report.findings]


def test_escape_hatch_suppresses_and_is_counted():
    report = run_fixture("dvt001_good.py")
    assert [f.code for f in report.suppressed] == ["DVT001"]
    report = run_fixture("dvt003_good.py")
    assert [f.code for f in report.suppressed] == ["DVT003"]
    assert "suppressed via escape hatch" in report.summary()


def test_full_tree_is_clean():
    """The CI contract behind `make lint`: zero findings on the package,
    with the drainer's bulk device_get as a counted escape hatch."""
    report = run_paths([PACKAGE], root=PACKAGE.parent)
    assert report.findings == [], "\n".join(f.render() for f in report.findings)
    assert any(f.code == "DVT003" and "engine" in f.path
               for f in report.suppressed)


def test_tree_annotations_are_load_bearing():
    """Mutation check: stripping one guarded write's lock in engine.py
    source must produce a DVT001 finding — proves the clean tree run is
    'checked and passed', not 'nothing registered'."""
    import ast

    from deep_vision_tpu.analysis.framework import FileContext
    from deep_vision_tpu.analysis.rules_locks import check_dvt001

    src = (PACKAGE / "serve" / "engine.py").read_text()
    ctx = FileContext(PACKAGE / "serve" / "engine.py", "engine.py", src)
    clean = check_dvt001(ctx)
    assert clean == []
    # graft an unlocked guarded write next to a BatchingEngine method
    anchor = "    def health_report("
    assert src.count(anchor) == 1
    mutated = src.replace(
        anchor,
        "    def _evil(self):\n        self.submitted += 1\n\n" + anchor, 1)
    assert mutated != src
    ctx2 = FileContext(PACKAGE / "serve" / "engine.py", "engine.py", mutated)
    bad = check_dvt001(ctx2)
    assert any("submitted" in f.message for f, _, _ in bad)


# -- runtime sanitizer -------------------------------------------------------


@pytest.fixture
def sani():
    was = sanitizer.enabled()
    sanitizer.enable(True)
    sanitizer.reset()
    yield sanitizer
    sanitizer.reset()
    sanitizer.enable(was)


def test_new_lock_is_plain_when_disabled():
    was = sanitizer.enabled()
    sanitizer.enable(False)
    try:
        lock = new_lock("test.plain")
        assert not isinstance(lock, SanitizedLock)
        with lock:
            pass
    finally:
        sanitizer.enable(was)


def test_sanitizer_raises_on_inversion(sani):
    a = new_lock("test.A._lock")
    b = new_lock("test.B._lock")
    assert isinstance(a, SanitizedLock)
    # establish A -> B on this thread
    with a:
        with b:
            pass
    # invert on another thread: B then A must raise before deadlocking
    caught = []

    def invert():
        try:
            with b:
                with a:
                    pass
        except LockOrderViolation as e:
            caught.append(e)

    t = threading.Thread(target=invert)
    t.start()
    t.join(5)
    assert caught, "inverted acquisition did not raise"
    assert sani.violations(), "violation was not recorded for the fixture"
    assert "test.A._lock" in str(caught[0])


def test_sanitizer_allows_consistent_order_and_reuse(sani):
    a = new_lock("test.A._lock")
    b = new_lock("test.B._lock")
    for _ in range(3):
        with a:
            with b:
                pass
    # same-site instances (e.g. two engine replicas) impose no ordering
    a2 = new_lock("test.A._lock")
    with a:
        with a2:
            pass
    assert sani.violations() == []


def test_sanitizer_reset_clears_graph(sani):
    a = new_lock("test.A._lock")
    b = new_lock("test.B._lock")
    with a:
        with b:
            pass
    sani.reset()
    with b:
        with a:  # no longer an inversion: the graph was cleared
            pass
    assert sani.violations() == []
