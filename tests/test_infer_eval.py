"""`infer eval` across task families: restores the best checkpoint and
reports the task's held-out metrics (classification top-1/5 — the
reference's ``validate()``; detection mAP@0.5 — upstream's "WIP")."""

import pytest

from deep_vision_tpu.cli import infer, train


@pytest.mark.slow
def test_eval_classification_from_checkpoint(tmp_path, mesh1, capsys):
    wd = str(tmp_path / "run")
    rc = train.main(["-m", "lenet5", "--synthetic", "--synthetic-size", "128",
                     "--epochs", "1", "--batch-size", "32",
                     "--workdir", wd])
    assert rc == 0
    rc = infer.main(["eval", "-m", "lenet5", "--workdir", wd,
                     "--synthetic", "--synthetic-size", "64",
                     "--batch-size", "32"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "top1=" in out and "top5=" in out


def test_eval_rejects_gan_configs(tmp_path):
    with pytest.raises(SystemExit, match="does not support"):
        infer.main(["eval", "-m", "dcgan", "--workdir", str(tmp_path),
                    "--synthetic"])
