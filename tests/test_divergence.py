"""Divergence guard (VERDICT r1 item 8): non-finite steps are skipped and
counted; a clearly-diverged run halts with an actionable error.

Reference context: the reference's only acknowledgment of NaNs is a TODO
around skipped validation losses (Hourglass/tensorflow/train.py:126-130) —
the framework does better: branch-free in-step skip + host-side halt.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.core.config import get_config
from deep_vision_tpu.core.trainer import Trainer
from deep_vision_tpu.data.loader import ArrayLoader
from deep_vision_tpu.data.mnist import synthetic_mnist
from deep_vision_tpu.tasks.classification import ClassificationTask


def make_trainer(tmp_path, mesh, lr=None, max_bad_steps=100):
    cfg = get_config("lenet5")
    cfg.total_epochs = 1
    cfg.batch_size = 32
    cfg.log_every_steps = 1
    cfg.max_bad_steps = max_bad_steps
    if lr is not None:
        cfg.optimizer.learning_rate = lr
    model = cfg.model()
    task = ClassificationTask(num_classes=10)
    return cfg, Trainer(cfg, model, task, mesh=mesh, workdir=str(tmp_path))


def test_nonfinite_step_is_skipped(tmp_path, mesh1):
    """A NaN batch must leave params/opt_state untouched and increment
    bad_steps; the step counter still advances."""
    cfg, trainer = make_trainer(tmp_path, mesh1)
    data = synthetic_mnist(64)
    train = ArrayLoader(data, cfg.batch_size, seed=1)
    sample = next(iter(train))
    state = trainer.init_state(sample)
    # fetch BEFORE stepping — the jitted step donates the state buffers
    p0 = jax.device_get(state.params)

    bad = dict(sample)
    bad["image"] = np.full_like(np.asarray(sample["image"]), np.nan)
    new_state, metrics = trainer.train_step(state, bad)
    assert int(jax.device_get(new_state.bad_steps)) == 1
    assert int(jax.device_get(new_state.step)) == 1
    p1 = jax.device_get(new_state.params)
    for a, b in zip(jax.tree_util.tree_leaves(p0),
                    jax.tree_util.tree_leaves(p1)):
        np.testing.assert_array_equal(a, b)

    # a good batch after the bad one still applies normally
    newer, _ = trainer.train_step(new_state, sample)
    assert int(jax.device_get(newer.bad_steps)) == 1
    changed = any(
        not np.array_equal(a, b)
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(
                            jax.device_get(newer.params))))
    assert changed


@pytest.mark.slow
def test_lr_blowup_halts(tmp_path, mesh1):
    """An absurd LR drives the weights past float32 range (inf logits →
    nan loss) within a few steps; the epoch loop must halt with a clear
    RuntimeError instead of training on garbage.  (1e6 alone keeps LeNet's
    tanh-bounded loss finite — overflow needs ~1e38.)"""
    cfg, trainer = make_trainer(tmp_path, mesh1, lr=1e38, max_bad_steps=3)
    data = synthetic_mnist(512)
    train = ArrayLoader(data, cfg.batch_size, seed=1)
    with pytest.raises(RuntimeError, match="diverged"):
        trainer.fit(train, None)


def test_restores_checkpoint_without_bad_steps(tmp_path, mesh1):
    """Checkpoints written before TrainState grew ``bad_steps`` must still
    restore (missing keys keep their fresh-state defaults)."""
    from deep_vision_tpu.core.checkpoint import Checkpointer
    from deep_vision_tpu.core.state import TrainState

    cfg, trainer = make_trainer(tmp_path, mesh1)
    data = synthetic_mnist(64)
    train = ArrayLoader(data, cfg.batch_size, seed=1)
    state = trainer.init_state(next(iter(train)))

    # simulate an old-layout checkpoint: payload without 'bad_steps'
    old_save_dict = TrainState.save_dict

    def legacy_save_dict(self):
        d = old_save_dict(self)
        d.pop("bad_steps")
        return d

    ckpt = Checkpointer(str(tmp_path / "legacy"))
    TrainState.save_dict = legacy_save_dict
    try:
        ckpt.save(7, state, extras={"epoch": 2})
    finally:
        TrainState.save_dict = old_save_dict

    restored, extras = ckpt.restore(state)
    assert extras["epoch"] == 2
    assert int(jax.device_get(restored.step)) == 0
    assert int(jax.device_get(restored.bad_steps)) == 0  # default kept


def test_checkpoint_layout_introspection(tmp_path, mesh1):
    """state_subtree_keys/has_state_key read stored-layout metadata
    without a restore — what cli.infer uses to tell a pipeline-trained
    params tree ({stem, stages}) from a monolithic one."""
    from deep_vision_tpu.core.checkpoint import Checkpointer

    cfg, trainer = make_trainer(tmp_path, mesh1)
    data = synthetic_mnist(64)
    state = trainer.init_state(next(iter(ArrayLoader(data, cfg.batch_size))))

    ckpt = Checkpointer(str(tmp_path / "introspect"))
    assert ckpt.state_subtree_keys("params") == set()  # no checkpoint yet
    ckpt.save(1, state, extras={})
    keys = ckpt.state_subtree_keys("params")
    assert keys and "stem" not in keys  # monolithic flax auto-names
    assert ckpt.state_subtree_keys("no_such_key") == set()
    assert not ckpt.has_state_key("ema_params")  # EMA off → {} stored


def test_guard_baseline_survives_resume(tmp_path, mesh1):
    """Skips recorded before a checkpoint must not count against the
    resumed run (review finding: lifetime cap across resumes)."""
    from deep_vision_tpu.core.state import DivergenceGuard

    guard = DivergenceGuard(limit=3)
    guard.set_baseline(90)  # restored counter from a previous run
    guard.check({"bad_steps": 92})  # only 2 new this run — fine
    with pytest.raises(RuntimeError, match="diverged"):
        guard.check({"bad_steps": 94})  # 4 new > limit 3


@pytest.mark.slow
def test_adversarial_guard_skips_nan(tmp_path, mesh1):
    """The multi-network guard: a NaN batch leaves ALL networks' params
    unchanged and counts one bad step."""
    from deep_vision_tpu.core.adversarial import AdversarialTrainer
    from deep_vision_tpu.models.gan import DCGANDiscriminator, DCGANGenerator
    from deep_vision_tpu.tasks.gan import DCGANTask

    cfg = get_config("dcgan")
    cfg.log_every_steps = 1
    task = DCGANTask(DCGANGenerator(), DCGANDiscriminator(), latent_dim=16)
    trainer = AdversarialTrainer(cfg, task, mesh=mesh1,
                                 workdir=str(tmp_path))
    batch = {"image": np.random.default_rng(0).uniform(
        -1, 1, (8, 28, 28, 1)).astype(np.float32)}
    states = trainer.init_states(batch)
    p0 = {k: jax.device_get(s.params) for k, s in states.items()}
    bad = {"image": np.full((8, 28, 28, 1), np.nan, np.float32)}
    rng = jax.random.PRNGKey(0)
    new_states, _, metrics = trainer.train_step(states, bad, rng)
    assert int(jax.device_get(metrics["bad_steps"])) == 1
    for k in p0:
        for a, b in zip(
                jax.tree_util.tree_leaves(p0[k]),
                jax.tree_util.tree_leaves(
                    jax.device_get(new_states[k].params))):
            np.testing.assert_array_equal(a, b)
