"""`make serve-smoke` (chained): the overload-defense contract end to
end through BOTH production wirings — a real LeNet backend built by
cli.serve.build_server with the brownout ladder armed, fronted by the
in-process gateway from cli.gateway.build_gateway with network fault
injection (conn_reset / slow_drip / blackhole) on the gateway→backend
hop.  Three sustained overload episodes (slow-compute fault + a
closed-loop client herd) must each step the ladder to >= L2 and release
back to L0 after the load stops; premium-tenant traffic through the
gateway sees ZERO 5xx across every episode; every /metrics line on both
tiers parses as prometheus text with the dvt_brownout_* series present;
and the gateway's granted retries stay inside the token-bucket budget
(<= burst x backends + ratio x successes, asserted from the
dvt_gateway_* counters).  docs/SERVING.md "Overload & brownout".
Run directly, not under pytest; chained into `make serve-smoke`."""

import argparse
import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/brownout_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODEL = "lenet5"
EPISODES = 3
HERD = 8                 # closed-loop clients per episode
RETRY_RATIO = 0.1
RETRY_BURST = 6.0

# prometheus text exposition: `name{labels} value` / `# HELP|TYPE ...`
_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$")


def _serve_args(workdir: str) -> argparse.Namespace:
    return argparse.Namespace(
        model=None, models=MODEL, workdir=workdir,
        stablehlo=None, host="127.0.0.1", port=0,
        # one request per 40 ms batch: a handful of concurrent clients
        # builds real queue pressure without needing real scale
        max_batch=1, max_wait_ms=1.0, buckets=None, max_queue=64,
        warmup=True, verbose=False, pipeline_depth=2,
        faults="compute:latency:delay_ms=40", fault_seed=0,
        serve_devices=1, shard_batches=False, wire_dtype="float32",
        infer_dtype="float32",
        hbm_budget_mb=0.0, shadow_frac=0.0, phase_timeout_s=60.0,
        # the ladder, tuned for smoke time scales: depth ~HERD x 40 ms
        # EWMA clears L3 (240 ms), release takes ~3 ticks + cooldown
        brownout=True, brownout_interval_ms=25.0,
        brownout_l1_ms=20.0, brownout_l2_ms=60.0, brownout_l3_ms=240.0,
        brownout_occupancy=0.97, brownout_shed_rate=0.9,
        brownout_up_window=2, brownout_down_window=3,
        brownout_cooldown_s=0.2, brownout_force=-1,
        qos=("premium:rate=0,shed_at=1.0,tenants=acme;"
             "standard:rate=0,shed_at=0.5;default=standard"))


def _gateway_args(backend_port: int) -> argparse.Namespace:
    return argparse.Namespace(
        backend=[f"127.0.0.1:{backend_port}"],
        host="127.0.0.1", port=0, probe_interval_ms=100.0,
        retry_budget=4, retry_budget_ratio=RETRY_RATIO,
        retry_budget_burst=RETRY_BURST,
        backoff_ms=1.0, backoff_max_ms=5.0,
        # bounded network chaos on the hop: 3 peer RSTs, 5 congested
        # (30 ms) attempts, one 0.2 s black hole — the retry budget must
        # absorb all of it without a client-visible 5xx
        faults=("gateway:conn_reset:times=3;"
                "gateway:slow_drip:delay_ms=30:times=5;"
                "gateway:blackhole:hang_s=0.2:times=1"),
        fault_seed=0,
        # chaos is injected, not organic: the breaker must not amplify
        # the smoke's own faults into an unroutable backend
        breaker_threshold=10, dead_after=10)


def _post(base: str, path: str, payload: dict, headers: dict = None,
          timeout: float = 60.0):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _brownout_stats(backend_base: str) -> dict:
    with urllib.request.urlopen(backend_base + "/v1/brownout",
                                timeout=30) as r:
        return json.loads(r.read())


def _wait_for(what: str, predicate, deadline_s: float = 30.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        out = predicate()
        if out is not None:
            return out
        time.sleep(0.05)
    raise AssertionError(f"timed out after {deadline_s}s waiting for {what}")


def _check_metrics(base: str) -> str:
    """Every exposition line must parse and carry a numeric value."""
    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        text = r.read().decode()
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        assert _METRIC_LINE.match(ln), f"unparseable metric: {ln!r}"
        float(ln.rsplit(" ", 1)[1])
    return text


def _metric_values(text: str, name: str) -> list[float]:
    out = []
    for ln in text.splitlines():
        if ln.startswith(name) and not ln.startswith("#"):
            head = ln.rsplit(" ", 1)[0]
            if head == name or head.startswith(name + "{"):
                out.append(float(ln.rsplit(" ", 1)[1]))
    return out


def smoke(workdir: str) -> None:
    from deep_vision_tpu.cli.gateway import build_gateway
    from deep_vision_tpu.cli.serve import build_server

    plane, backend = build_server(_serve_args(workdir))
    backend.start_background()
    backend_base = f"http://{backend.host}:{backend.port}"
    gw, gwsrv = build_gateway(_gateway_args(backend.port))
    gwsrv.start_background()
    base = f"http://127.0.0.1:{gwsrv.port}"
    rng = np.random.default_rng(0)
    imgs = [rng.uniform(0.0, 1.0, (32, 32, 1)).tolist()
            for _ in range(4)]
    path = f"/v1/models/{MODEL}/classify"
    try:
        bo = _brownout_stats(backend_base)
        assert bo["level"] == 0 and bo["forced"] is None, bo

        served = [0]
        sheds = [0]
        fivexx = []            # any client-visible 5xx, any tenant
        premium_fivexx = []    # the hard contract: must stay empty
        max_level = [0]
        lock = threading.Lock()

        def hammer(stop):
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    s, out, _ = _post(base, path,
                                      {"pixels": imgs[i % len(imgs)]})
                    assert s == 200 and out["top"], out
                    with lock:
                        served[0] += 1
                except urllib.error.HTTPError as e:
                    e.read()
                    with lock:
                        if e.code >= 500:
                            fivexx.append(f"standard {e.code}")
                        else:
                            sheds[0] += 1
                    time.sleep(0.02)   # a shed client backs off a beat
                except Exception as e:  # noqa: BLE001 — any transport failure is a lost request
                    with lock:
                        fivexx.append(repr(e))

        for episode in range(1, EPISODES + 1):
            stop = threading.Event()
            threads = [threading.Thread(target=hammer, args=(stop,),
                                        daemon=True) for _ in range(HERD)]
            for t in threads:
                t.start()

            def level_at_least_2():
                lvl = _brownout_stats(backend_base)["level"]
                max_level[0] = max(max_level[0], lvl)
                return lvl if lvl >= 2 else None

            _wait_for(f"episode {episode}: ladder >= L2 under overload",
                      level_at_least_2)
            # premium rides THROUGH the same saturated gateway+backend:
            # shed_at=1.0 plus the L3 premium carve-out means it may
            # queue, but it never sees a server error
            for _ in range(5):
                try:
                    s, out, _ = _post(base, path, {"pixels": imgs[0]},
                                      headers={"X-DVT-Tenant": "acme"})
                    assert s == 200 and out["top"], out
                except urllib.error.HTTPError as e:
                    e.read()
                    if e.code >= 500:
                        premium_fivexx.append(f"premium {e.code}")
            stop.set()
            for t in threads:
                t.join(30)
            _wait_for(
                f"episode {episode}: release back to L0 after the load",
                lambda: (lambda lvl: 0 if lvl == 0 else None)(
                    _brownout_stats(backend_base)["level"]))

        assert premium_fivexx == [], premium_fivexx
        assert fivexx == [], fivexx[:5]
        assert max_level[0] >= 2 and served[0] > 0, (max_level, served)
        bo = _brownout_stats(backend_base)
        assert bo["level"] == 0, bo
        assert bo["transitions_up"] >= EPISODES, bo
        assert bo["transitions_down"] >= bo["transitions_up"], bo

        # -- /metrics on BOTH tiers: every line parses ----------------
        btext = _check_metrics(backend_base)
        for series in ("dvt_brownout_level",
                       "dvt_brownout_transitions_total",
                       "dvt_brownout_level_entries_total",
                       "dvt_brownout_pressure_ms",
                       "dvt_brownout_ticks_total"):
            assert series in btext, f"missing {series} in backend /metrics"
        assert _metric_values(btext, "dvt_brownout_level") == [0.0]

        gtext = _check_metrics(base)
        # the budget invariant, from the exported counters alone: the
        # chaos spec forced retries, but never past the token bucket
        retries = sum(_metric_values(gtext, "dvt_gateway_retries_total"))
        successes = sum(_metric_values(
            gtext, "dvt_gateway_backend_successes_total"))
        assert retries >= 1, "fault injection never forced a retry"
        assert retries <= RETRY_BURST * len(gw.backends) \
            + RETRY_RATIO * successes + 1e-9, (retries, successes)
        fired = sum(f.fired for f in gw.faults.faults)
        assert fired >= 4, f"only {fired} gateway faults fired"
        print(f"brownout-smoke PASS: {EPISODES} overload episodes "
              f"(max level L{max_level[0]}, "
              f"{bo['transitions_up']} up / {bo['transitions_down']} "
              f"down transitions), {served[0]} served + {sheds[0]} "
              f"sheds, premium 5xx-free through {fired} injected "
              f"network faults; gateway retries {retries:g} within "
              f"budget (burst {RETRY_BURST:g}, ratio {RETRY_RATIO:g}, "
              f"{successes:g} successes); all /metrics lines parsed "
              f"on both tiers")
    finally:
        gwsrv.shutdown()
        gw.stop()
        backend.shutdown()
        plane.stop(drain_deadline=5.0)


def main():
    with tempfile.TemporaryDirectory() as workdir:
        os.makedirs(os.path.join(workdir, MODEL), exist_ok=True)
        smoke(workdir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
