"""Worker for test_distributed_two_processes: one of N processes in a
CPU 'pod'.  Run: python dist_worker.py <coordinator> <process_id> <n>.

Must be a real script (not -c/stdin): jax.distributed spawns service
threads, and the parent must be able to reap us cleanly on failure.
"""

import os
import sys

# 2 virtual CPU devices per process, BEFORE any jax import
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if not f.startswith("--xla_force_host_platform_device_count")]
flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize pins the TPU

import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from deep_vision_tpu.parallel.distributed import (  # noqa: E402
    initialize,
    make_pod_mesh,
)


def main():
    coordinator, pid, nprocs = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    initialize(coordinator_address=coordinator, num_processes=nprocs,
               process_id=pid)
    assert jax.process_count() == nprocs, jax.process_count()
    assert jax.process_index() == pid
    n_global = len(jax.devices())
    n_local = len(jax.local_devices())
    assert n_global == 2 * nprocs and n_local == 2, (n_global, n_local)

    mesh = make_pod_mesh({"data": -1})
    assert dict(mesh.shape) == {"data": n_global}, mesh.shape

    # a real cross-process collective: every process contributes its
    # local shard, the jitted global sum must see all of them
    local = np.full((n_local,), float(pid + 1), np.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local, (n_global,))
    total = jax.jit(lambda x: x.sum(),
                    out_shardings=NamedSharding(mesh, P()))(arr)
    val = float(np.asarray(total.addressable_shards[0].data))
    expect = sum(2.0 * (i + 1) for i in range(nprocs))
    assert val == expect, (val, expect)
    print(f"RESULT pid={pid} sum={val}", flush=True)


if __name__ == "__main__":
    main()
