"""`make quant-smoke`: the int8 serving path end to end over real HTTP.

Boots the exact `python -m deep_vision_tpu.cli.serve` wiring
(cli.serve.build_server) twice against the SAME LeNet workdir fixture —
once at --infer-dtype float32, once at --infer-dtype int8 (which
calibrates on deterministic synthetic batches at load, quantizes the
weights per-channel, and serves int8-resident weights through the
fused Pallas ingest, interpret-mode on CPU) — classifies the same raw
uint8 images through both lanes, and gates on:

  * top-1 agreement between the int8 and f32 answers (the accuracy
    gate `--infer-dtype int8` is priced by, docs/SERVING.md);
  * /v1/models exposing the quant block (act_scale, calib provenance,
    true param_bytes, chosen ingest path);
  * /v1/stats reporting weight_hbm_bytes ≤ 0.27× the f32 lane's.

Run directly, not under pytest (chained into `make serve-smoke`)."""

import argparse
import json
import os
import sys
import tempfile
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/quant_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_IMAGES = 8


def smoke_lane(workdir: str, infer_dtype: str, images) -> dict:
    """One serve lane: boot, classify every image over HTTP, return
    {"top1": [...], "weight_hbm_bytes": int, "describe": dict}."""
    from deep_vision_tpu.cli.serve import build_server

    args = argparse.Namespace(
        model="lenet5", workdir=workdir, stablehlo=None,
        host="127.0.0.1", port=0, max_batch=4, max_wait_ms=2.0,
        buckets=None, max_queue=64, warmup=False, verbose=False,
        pipeline_depth=2, faults="", fault_seed=0,
        serve_devices=1, shard_batches=False,
        wire_dtype="uint8", infer_dtype=infer_dtype,
        calib_batches=2, calib_dir=None)
    engine, server = build_server(args)
    server.start_background()
    base = f"http://{server.host}:{server.port}"
    try:
        top1 = []
        for img in images:
            req = urllib.request.Request(
                base + "/v1/classify",
                data=json.dumps({"pixels": img.tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200, f"expected 200, got {r.status}"
                top1.append(json.loads(r.read())["top"][0]["class"])
        with urllib.request.urlopen(base + "/v1/stats", timeout=60) as r:
            stats = json.loads(r.read())["lenet5"]
        assert stats["infer_dtype"] == infer_dtype, stats["infer_dtype"]
        with urllib.request.urlopen(base + "/v1/models", timeout=60) as r:
            desc = json.loads(r.read())["models"]["lenet5"]["model"]
        return {"top1": top1,
                "weight_hbm_bytes": stats["weight_hbm_bytes"],
                "describe": desc}
    finally:
        server.shutdown()
        engine.stop(drain_deadline=5.0)


def main():
    with tempfile.TemporaryDirectory() as workdir:
        # empty workdir: restore falls back to deterministic random
        # init, so BOTH lanes serve the same weights — agreement
        # measures quantization error only
        rng = np.random.default_rng(0)
        images = [rng.integers(0, 256, (32, 32, 1), dtype=np.uint8)
                  for _ in range(N_IMAGES)]
        f32 = smoke_lane(workdir, "float32", images)
        i8 = smoke_lane(workdir, "int8", images)

    agree = sum(a == b for a, b in zip(f32["top1"], i8["top1"]))
    assert agree >= N_IMAGES - 1, \
        f"int8 top-1 agreed on {agree}/{N_IMAGES} vs f32: " \
        f"{i8['top1']} vs {f32['top1']}"

    quant = i8["describe"].get("quant")
    assert quant, i8["describe"]
    assert quant["act_scale"] > 0, quant
    assert quant["calib_source"] == "synthetic", quant
    assert quant["calib_batches"] == 2, quant
    assert quant["ingest"] in ("pallas", "xla"), quant
    assert "quant" not in f32["describe"], f32["describe"]

    ratio = i8["weight_hbm_bytes"] / f32["weight_hbm_bytes"]
    assert ratio <= 0.27, \
        f"int8 weight HBM {i8['weight_hbm_bytes']} is {ratio:.4f}x " \
        f"the f32 lane's {f32['weight_hbm_bytes']} (gate: 0.27)"

    print(f"quant-smoke PASS: int8 top-1 agreed {agree}/{N_IMAGES} "
          f"with f32 over HTTP, weight HBM {i8['weight_hbm_bytes']} B "
          f"= {ratio:.4f}x f32 ({f32['weight_hbm_bytes']} B), "
          f"act_scale {quant['act_scale']:.6f} "
          f"({quant['calib_source']}, {quant['calib_batches']} batches), "
          f"ingest {quant['ingest']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
