"""Spatial (context) parallelism: halo-exchange conv over an 8-way
row-sharded mesh must equal the unsharded conv."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.parallel import make_mesh
from deep_vision_tpu.parallel.spatial import SPATIAL_AXIS, spatial_conv


@pytest.fixture(scope="module")
def spatial_mesh():
    return make_mesh({SPATIAL_AXIS: 8})


def _reference_conv(x, k, strides=(1, 1)):
    return jax.lax.conv_general_dilated(
        x, k, window_strides=strides, padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


@pytest.mark.parametrize("kh", [1, 3, 5])
def test_spatial_conv_matches_unsharded(spatial_mesh, kh):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 32, 16, 3)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(kh, 3, 3, 4)).astype(np.float32) * 0.1)
    got = spatial_conv(x, k, spatial_mesh)
    want = _reference_conv(x, k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_spatial_conv_composes_with_data_axis():
    mesh = make_mesh({"data": 2, SPATIAL_AXIS: 4})
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 16, 8, 2)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(3, 3, 2, 2)).astype(np.float32) * 0.1)
    got = spatial_conv(x, k, mesh)
    want = _reference_conv(x, k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_make_pod_mesh_single_slice():
    from deep_vision_tpu.parallel.distributed import initialize, make_pod_mesh

    initialize()  # no-op single host
    mesh = make_pod_mesh({"data": -1})
    assert mesh.shape["data"] == 8  # all virtual devices on the data axis
    mesh2 = make_pod_mesh({"data": -1, "model": 2})
    assert mesh2.shape == {"data": 4, "model": 2}


def test_spatial_conv_rejects_strides(spatial_mesh):
    x = jnp.zeros((1, 16, 8, 2))
    k = jnp.zeros((3, 3, 2, 2))
    with pytest.raises(ValueError, match="strides"):
        spatial_conv(x, k, spatial_mesh, strides=(2, 2))
