"""Spatial (context) parallelism: halo-exchange conv over an 8-way
row-sharded mesh must equal the unsharded conv."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.parallel import make_mesh
from deep_vision_tpu.parallel.spatial import SPATIAL_AXIS, spatial_conv


@pytest.fixture(scope="module")
def spatial_mesh():
    return make_mesh({SPATIAL_AXIS: 8})


def _reference_conv(x, k, strides=(1, 1)):
    return jax.lax.conv_general_dilated(
        x, k, window_strides=strides, padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


@pytest.mark.parametrize("kh", [1, 3, 5])
def test_spatial_conv_matches_unsharded(spatial_mesh, kh):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 32, 16, 3)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(kh, 3, 3, 4)).astype(np.float32) * 0.1)
    got = spatial_conv(x, k, spatial_mesh)
    want = _reference_conv(x, k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_spatial_conv_composes_with_data_axis():
    mesh = make_mesh({"data": 2, SPATIAL_AXIS: 4})
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 16, 8, 2)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(3, 3, 2, 2)).astype(np.float32) * 0.1)
    got = spatial_conv(x, k, mesh)
    want = _reference_conv(x, k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_make_pod_mesh_single_slice():
    from deep_vision_tpu.parallel.distributed import initialize, make_pod_mesh

    initialize()  # no-op single host
    mesh = make_pod_mesh({"data": -1})
    assert mesh.shape["data"] == 8  # all virtual devices on the data axis
    mesh2 = make_pod_mesh({"data": -1, "model": 2})
    assert mesh2.shape == {"data": 4, "model": 2}


@pytest.mark.parametrize("kh,strides", [
    (3, (2, 2)),   # ResNet downsample 3×3/2 (SAME pads the bottom row only)
    (1, (2, 2)),   # bottleneck projection 1×1/2 (no padding at all)
    (7, (2, 2)),   # ResNet stem 7×7/2 (pad 2 above, 3 below)
    (5, (2, 1)),   # mixed row/col strides
])
def test_spatial_conv_strided_matches_unsharded(spatial_mesh, kh, strides):
    """SAME-under-stride pads asymmetrically; the asymmetric halo must
    reproduce it exactly (every conv shape ResNet uses)."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 64, 16, 3)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(kh, 3, 3, 4)).astype(np.float32) * 0.1)
    got = spatial_conv(x, k, spatial_mesh, strides=strides)
    want = _reference_conv(x, k, strides=strides)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("window,strides", [
    ((2, 2), None),      # Hourglass downsample (stride defaults to window)
    ((3, 3), (2, 2)),    # ResNet stem pool (SAME pads bottom row only)
    ((3, 3), (1, 1)),    # YOLO-tiny style stride-1 pool
])
def test_spatial_max_pool_matches_unsharded(spatial_mesh, window, strides):
    from flax import linen as nn

    from deep_vision_tpu.parallel.spatial import spatial_max_pool

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 64, 16, 3)).astype(np.float32))
    got = spatial_max_pool(x, window, strides, mesh=spatial_mesh)
    want = nn.max_pool(x, window, strides or window, padding="SAME")
    assert got.shape == want.shape
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_spatial_ops_compose_resnet_stem(spatial_mesh):
    """With strided convs + pooling, the explicit API runs a real model's
    downsampling path: ResNet stem (7×7/2 conv → 3×3/2 max-pool) followed
    by a 3×3 block conv, sharded 8 ways, matching the unsharded pipeline.
    224 rows → 112 → 56: every stage keeps rows divisible by the mesh."""
    from flax import linen as nn

    from deep_vision_tpu.parallel.spatial import spatial_max_pool

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(1, 224, 32, 3)).astype(np.float32))
    k_stem = jnp.asarray(
        rng.normal(size=(7, 7, 3, 8)).astype(np.float32) * 0.05)
    k_block = jnp.asarray(
        rng.normal(size=(3, 3, 8, 8)).astype(np.float32) * 0.05)

    got = spatial_conv(x, k_stem, spatial_mesh, strides=(2, 2))
    got = spatial_max_pool(got, (3, 3), (2, 2), mesh=spatial_mesh)
    got = spatial_conv(got, k_block, spatial_mesh)

    want = _reference_conv(x, k_stem, strides=(2, 2))
    want = nn.max_pool(want, (3, 3), (2, 2), padding="SAME")
    want = _reference_conv(want, k_block)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_spatial_conv_rejects_misaligned_stride(spatial_mesh):
    # 8 shards × 4 rows each; stride 3 doesn't divide the shard rows, so
    # output rows would straddle shard boundaries
    x = jnp.zeros((1, 32, 8, 2))
    k = jnp.zeros((3, 3, 2, 2))
    with pytest.raises(ValueError, match="stride"):
        spatial_conv(x, k, spatial_mesh, strides=(3, 1))


@pytest.mark.slow
def test_trainer_spatial_mesh_matches_unsharded(tmp_path, mesh1):
    """VERDICT r1 item 10: spatial parallelism must be REAL — a conv net
    trained end-to-end under the Trainer on a {"data":2, "spatial":4} mesh
    (batch rows sharded over ``spatial``; GSPMD inserts the conv halo
    exchanges) must match the single-device run's losses/metrics."""
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.loader import ArrayLoader
    from deep_vision_tpu.data.mnist import synthetic_mnist
    from deep_vision_tpu.tasks.classification import ClassificationTask

    def run(mesh, workdir):
        cfg = get_config("lenet5")
        cfg.total_epochs = 2
        cfg.batch_size = 32
        model = cfg.model()
        trainer = Trainer(cfg, model, ClassificationTask(10), mesh=mesh,
                          workdir=workdir)
        data = synthetic_mnist(128)  # 28×28 images: H=28 % 4 == 0
        train = ArrayLoader(data, cfg.batch_size, seed=1)
        val = ArrayLoader(data, cfg.batch_size, shuffle=False)
        state = trainer.fit(train, val)
        return trainer.evaluate(state, val)

    sp_mesh = make_mesh({"data": 2, SPATIAL_AXIS: 4})
    m_sp = run(sp_mesh, str(tmp_path / "sp"))
    m_1 = run(mesh1, str(tmp_path / "single"))
    # same data, same seeds → same training trajectory up to fp reduction
    # order; the sharded run must genuinely learn AND agree numerically
    assert m_sp["top1"] > 0.9
    np.testing.assert_allclose(m_sp["loss"], m_1["loss"], rtol=2e-2,
                               atol=2e-3)


@pytest.mark.slow
def test_trainer_fit_yolo_on_mixed_mesh(tmp_path, mesh1):
    """VERDICT r2 #5: the REAL Trainer.fit loop (not a hand-built step)
    training the detection stack for 2 epochs on a {data:2, spatial:2}
    mesh — 3-scale y_true grids ride the data axis (odd grid sizes fall
    back from spatial sharding), images shard rows — and must agree with
    the single-device trajectory."""
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.detection import (
        DetectionLoader,
        synthetic_detection_dataset,
    )
    from deep_vision_tpu.tasks.detection import YoloTask

    samples = synthetic_detection_dataset(8, 64, 3, seed=11)

    def run(mesh, workdir):
        cfg = get_config("yolov3_toy")
        cfg.total_epochs = 2
        cfg.checkpoint_every_epochs = 1000
        train = DetectionLoader(samples, 8, 3, 64, train=True,
                                augment=False, seed=0)
        val = DetectionLoader(samples, 8, 3, 64, train=False)
        trainer = Trainer(cfg, cfg.model(), YoloTask(3), mesh=mesh,
                          workdir=workdir)
        state = trainer.fit(train, None)
        return trainer.evaluate(state, val)

    m_mix = run(make_mesh({"data": 2, SPATIAL_AXIS: 2},
                          devices=jax.devices()[:4]), str(tmp_path / "mix"))
    m_1 = run(mesh1, str(tmp_path / "single"))
    assert np.isfinite(m_mix["loss"])
    np.testing.assert_allclose(m_mix["loss"], m_1["loss"], rtol=2e-2)


@pytest.mark.slow
def test_trainer_fit_resnet_spatial_mode(tmp_path):
    """VERDICT r4 item 2: spatial as a TRAINING mode on a deep CNN — a
    ResNet-50 ``fit()`` on {data:2, spatial:4} (stride-2 convs, maxpool,
    BN all spatially partitioned by GSPMD) must trajectory-match the pure
    data-parallel {data:8} run.  BN semantics coincide exactly (both
    reduce over the global batch), so the tolerance covers only fp
    reduction order."""
    import jax.numpy as jnp

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.loader import ArrayLoader
    from deep_vision_tpu.data.synthetic import synthetic_classification
    from deep_vision_tpu.models.resnet import ResNet50
    from deep_vision_tpu.tasks.classification import ClassificationTask

    def run(mesh_axes, workdir):
        cfg = get_config("resnet50")
        cfg.batch_size = 8
        cfg.image_size = 64
        cfg.half_precision = False
        cfg.num_classes = 10
        cfg.optimizer.name = "sgd"  # Adam amplifies zero-grad float noise
        cfg.model = lambda: ResNet50(dtype=jnp.float32, num_classes=10)
        mesh = make_mesh(mesh_axes)
        trainer = Trainer(cfg, cfg.model(), ClassificationTask(10),
                          mesh=mesh, workdir=workdir)
        data = synthetic_classification(24, 64, 3, 10, seed=5)
        loader = ArrayLoader(data, 8, seed=7, shuffle=False)
        state = trainer.init_state(next(iter(loader)))
        losses = []
        for i, b in enumerate(loader):
            if i >= 3:
                break
            state, m = trainer.train_step(state, dict(b))
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    dp = run({"data": 8}, str(tmp_path / "dp"))
    sp = run({"data": 2, SPATIAL_AXIS: 4}, str(tmp_path / "sp"))
    assert np.isfinite(sp).all()
    np.testing.assert_allclose(sp, dp, rtol=1e-3, atol=1e-3)


@pytest.mark.slow
def test_cli_train_spatial_mesh(tmp_path, capsys):
    """The full CLI path: ``cli.train -m resnet50 --mesh data=2,spatial=4``
    trains end to end with row-sharded inputs — the memory-lever mode
    PERF.md pairs with the reference's OOM coping
    (ResNet/pytorch/train.py batch 256→?, VGG README batch 128→64)."""
    from deep_vision_tpu.cli import train as cli_train

    rc = cli_train.main([
        "-m", "resnet50", "--synthetic", "--synthetic-size", "16",
        "--epochs", "1", "--batch-size", "8", "--image-size", "64",
        "--mesh", "data=2,spatial=4",
        "--workdir", str(tmp_path / "w")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "'spatial': 4" in out and "final:" in out


def test_shard_batch_spatial_placement():
    """Image leaves get P(data, spatial, ...); non-divisible or low-rank
    leaves fall back to data-only sharding."""
    from deep_vision_tpu.parallel import shard_batch

    mesh = make_mesh({"data": 2, SPATIAL_AXIS: 4})
    batch = {
        "image": np.zeros((4, 32, 32, 3), np.float32),
        "label": np.zeros((4,), np.int32),
        "odd_grid": np.zeros((4, 13, 13, 3, 8), np.float32),  # 13 % 4 != 0
    }
    placed = shard_batch(batch, mesh)
    img_spec = placed["image"].sharding.spec
    assert tuple(img_spec)[:2] == ("data", SPATIAL_AXIS)
    assert tuple(placed["label"].sharding.spec) == ("data",)
    odd = tuple(placed["odd_grid"].sharding.spec)
    assert SPATIAL_AXIS not in odd
