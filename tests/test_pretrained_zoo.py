"""Torch-weight import parity across the published-accuracy zoo (VERDICT r4
item 1): AlexNet V1/V2, VGG-16/19, Inception V1, MobileNet V1, LeNet-5 —
every architecture whose trained numbers the reference publishes
(AlexNet/VGG/Inception/MobileNet/LeNet ``pytorch/README.md``), so each
number is one ``cli.infer eval --pretrained`` away from verification.

Pattern follows test_pretrained.py: build a torch net with the REFERENCE's
exact module layout (the state_dict key format the published checkpoints
use), random weights, eval mode, and require logits parity through the
importer.  Runs fully air-gapped.  BN nets randomize affines near 1 so
scale attenuation can't mask placement/padding bugs (see
test_pretrained._randomize_bn_stats).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402
import torch.nn.functional as tfun  # noqa: E402

import jax.numpy as jnp  # noqa: E402

from deep_vision_tpu.models.pretrained import (  # noqa: E402
    import_torch_alexnet,
    import_torch_inception_v1,
    import_torch_lenet5,
    import_torch_mobilenet_v1,
    import_torch_vgg,
)

from tests.test_pretrained import _randomize_bn_stats  # noqa: E402


def _fill(net, gen, scale=0.05):
    with torch.no_grad():
        for p in net.parameters():
            p.copy_(torch.randn(p.shape, generator=gen) * scale)


def _parity(net, imported, flax_model, size, channels=3, gen=None,
            atol=2e-4, rtol=1e-3):
    with torch.no_grad():
        net.eval()
        x = torch.randn(2, channels, size, size, generator=gen)
        ref = net(x).numpy()
    out = flax_model.apply(
        {"params": imported["params"],
         "batch_stats": imported["batch_stats"]},
        jnp.asarray(x.numpy().transpose(0, 2, 3, 1)), train=False)
    np.testing.assert_allclose(np.asarray(out), ref, atol=atol, rtol=rtol)
    return x


# ---------------------------------------------------------------- AlexNet

def _torch_alexnet(widths, num_classes=1000):
    """Reference Sequential layout (AlexNet/pytorch/models/alexnet_v1.py
    :27-117, alexnet_v2.py:30-64): conv indices 0/4/8/10/12, classifier
    linears 1/4/6, LRN(width) after each of the first two ReLUs."""
    f = widths
    feats = tnn.Sequential(
        tnn.Conv2d(3, f[0], 11, 4, 2), tnn.ReLU(),
        tnn.LocalResponseNorm(f[0]), tnn.MaxPool2d(3, 2),
        tnn.Conv2d(f[0], f[1], 5, 1, 2), tnn.ReLU(),
        tnn.LocalResponseNorm(f[1]), tnn.MaxPool2d(3, 2),
        tnn.Conv2d(f[1], f[2], 3, 1, 1), tnn.ReLU(),
        tnn.Conv2d(f[2], f[3], 3, 1, 1), tnn.ReLU(),
        tnn.Conv2d(f[3], f[4], 3, 1, 1), tnn.ReLU(),
        tnn.MaxPool2d(3, 2))
    clf = tnn.Sequential(
        tnn.Dropout(), tnn.Linear(6 * 6 * f[4], 4096), tnn.ReLU(),
        tnn.Dropout(), tnn.Linear(4096, 4096), tnn.ReLU(),
        tnn.Linear(4096, num_classes))

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.features = feats
            self.classifier = clf

        def forward(self, x):
            return self.classifier(torch.flatten(self.features(x), 1))

    return Net()


@pytest.mark.slow
def test_alexnet_v1_import_forward_parity():
    from deep_vision_tpu.models.alexnet import AlexNetV1

    gen = torch.Generator().manual_seed(10)
    net = _torch_alexnet((96, 256, 384, 384, 256), num_classes=12)
    _fill(net, gen)
    _parity(net, import_torch_alexnet(net.state_dict()),
            AlexNetV1(num_classes=12), 224, gen=gen)


def test_alexnet_v2_import_forward_parity():
    from deep_vision_tpu.models.alexnet import AlexNetV2

    gen = torch.Generator().manual_seed(11)
    net = _torch_alexnet((64, 192, 384, 384, 256), num_classes=12)
    _fill(net, gen)
    _parity(net, import_torch_alexnet(net.state_dict()),
            AlexNetV2(num_classes=12), 224, gen=gen)


# ------------------------------------------------------------------- VGG

def _torch_vgg(plan, num_classes=1000):
    """Reference/torchvision Sequential layout (VGG/pytorch/models/
    vgg16.py:25-99): 3×3 pad-1 convs interleaved with ReLU and 2×2
    maxpools; classifier Linear/ReLU/Dropout ×2 + Linear."""
    layers, in_ch = [], 3
    for item in plan:
        if item == "M":
            layers.append(tnn.MaxPool2d(2, 2))
        else:
            layers += [tnn.Conv2d(in_ch, item, 3, 1, 1), tnn.ReLU()]
            in_ch = item
    clf = tnn.Sequential(
        tnn.Linear(7 * 7 * 512, 4096), tnn.ReLU(), tnn.Dropout(),
        tnn.Linear(4096, 4096), tnn.ReLU(), tnn.Dropout(),
        tnn.Linear(4096, num_classes))

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.features = tnn.Sequential(*layers)
            self.classifier = clf

        def forward(self, x):
            return self.classifier(torch.flatten(self.features(x), 1))

    return Net()


@pytest.mark.slow
def test_vgg16_import_forward_parity():
    from deep_vision_tpu.models.vgg import _VGG16, VGG16

    gen = torch.Generator().manual_seed(12)
    net = _torch_vgg(_VGG16, num_classes=7)
    _fill(net, gen)
    _parity(net, import_torch_vgg(net.state_dict()),
            VGG16(num_classes=7), 224, gen=gen)


@pytest.mark.slow
def test_vgg19_import_forward_parity():
    from deep_vision_tpu.models.vgg import _VGG19, VGG19

    gen = torch.Generator().manual_seed(13)
    net = _torch_vgg(_VGG19, num_classes=7)
    _fill(net, gen)
    _parity(net, import_torch_vgg(net.state_dict()),
            VGG19(num_classes=7), 224, gen=gen)


# ----------------------------------------------------------------- LeNet

def _torch_lenet5(num_classes=10):
    """Reference layout (LeNet/pytorch/models/lenet5.py:24-58): conv
    indices 0/4/8 (tanh + avgpool interleaved), classifier linears 0/2."""
    feats = tnn.Sequential(
        tnn.Conv2d(1, 6, 5), tnn.Tanh(), tnn.AvgPool2d(2, 2), tnn.Tanh(),
        tnn.Conv2d(6, 16, 5), tnn.Tanh(), tnn.AvgPool2d(2, 2), tnn.Tanh(),
        tnn.Conv2d(16, 120, 5), tnn.Tanh())
    clf = tnn.Sequential(tnn.Linear(120, 84), tnn.Tanh(),
                         tnn.Linear(84, num_classes))

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.features = feats
            self.classifier = clf

        def forward(self, x):
            return self.classifier(torch.flatten(self.features(x), 1))

    return Net()


def test_lenet5_import_forward_parity():
    from deep_vision_tpu.models.lenet import LeNet5

    gen = torch.Generator().manual_seed(14)
    net = _torch_lenet5()
    _fill(net, gen, scale=0.2)
    _parity(net, import_torch_lenet5(net.state_dict()),
            LeNet5(), 32, channels=1, gen=gen)


# ------------------------------------------------------------- MobileNet

class _TConvBN(tnn.Module):
    def __init__(self, i, o, k, s, p, groups=1):
        super().__init__()
        self.conv = tnn.Conv2d(i, o, k, s, p, groups=groups, bias=False)
        self.bn = tnn.BatchNorm2d(o)

    def forward(self, x):
        return tfun.relu(self.bn(self.conv(x)))


class _TDWSep(tnn.Module):
    """Reference DepthwiseSeparableConv (MobileNet/pytorch/models/
    mobilenet_v1.py:98-155): ``dw``/``pw`` children each with conv+bn."""

    def __init__(self, i, o, s):
        super().__init__()
        self.dw = _TConvBN(i, i, 3, s, 1, groups=i)
        self.pw = _TConvBN(i, o, 1, 1, 0)

    def forward(self, x):
        return self.pw(self.dw(x))


def _torch_mobilenet_v1(num_classes=1000):
    plan = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
            (256, 256, 1), (256, 512, 2), (512, 512, 1), (512, 512, 1),
            (512, 512, 1), (512, 512, 1), (512, 512, 1), (512, 1024, 2),
            (1024, 1024, 1)]
    feats = tnn.Sequential(
        tnn.Conv2d(3, 32, 3, 2, 1, bias=False), tnn.BatchNorm2d(32),
        tnn.ReLU(),
        *[_TDWSep(i, o, s) for i, o, s in plan],
        tnn.AdaptiveAvgPool2d((1, 1)))

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.features = feats
            self.linear = tnn.Linear(1024, num_classes)

        def forward(self, x):
            return self.linear(torch.flatten(self.features(x), 1))

    return Net()


def test_mobilenet_v1_import_forward_parity():
    from deep_vision_tpu.models.mobilenet import MobileNetV1

    gen = torch.Generator().manual_seed(15)
    net = _torch_mobilenet_v1(num_classes=9)
    _fill(net, gen)
    _randomize_bn_stats(net, gen)  # affines near 1: unmask padding bugs
    # 64² input walks stride-2 blocks through even sizes 64/32/16/8 — the
    # exact sites where XLA SAME and torch pad-1 placement diverge
    _parity(net, import_torch_mobilenet_v1(net.state_dict()),
            MobileNetV1(num_classes=9), 64, gen=gen)


# ------------------------------------------------------------- Inception

class _TBasicConv(tnn.Module):
    """Reference BasicConv2d (inception_v1.py:193-201): conv+bias → ReLU."""

    def __init__(self, i, o, k, **kw):
        super().__init__()
        self.conv = tnn.Conv2d(i, o, k, **kw)

    def forward(self, x):
        return tfun.relu(self.conv(x))


class _TInceptionModule(tnn.Module):
    def __init__(self, i, c1, c3r, c3, c5r, c5, cp):
        super().__init__()
        self.branch1_conv1x1 = _TBasicConv(i, c1, 1)
        self.branch2_conv1x1 = _TBasicConv(i, c3r, 1)
        self.branch2_conv3x3 = _TBasicConv(c3r, c3, 3, padding=1)
        self.branch3_conv1x1 = _TBasicConv(i, c5r, 1)
        self.branch3_conv5x5 = _TBasicConv(c5r, c5, 5, padding=2)
        self.branch4_maxpool = tnn.MaxPool2d(3, 1, padding=1)
        self.branch4_conv1x1 = _TBasicConv(i, cp, 1)

    def forward(self, x):
        return torch.cat(
            [self.branch1_conv1x1(x),
             self.branch2_conv3x3(self.branch2_conv1x1(x)),
             self.branch3_conv5x5(self.branch3_conv1x1(x)),
             self.branch4_conv1x1(self.branch4_maxpool(x))], 1)


class _TAux(tnn.Module):
    def __init__(self, i, num_classes=1000):
        super().__init__()
        self.features = tnn.Sequential(tnn.AvgPool2d(5, 3),
                                       _TBasicConv(i, 128, 1))
        self.classifier = tnn.Sequential(
            tnn.Linear(4 * 4 * 128, 1024), tnn.ReLU(), tnn.Dropout(0.7),
            tnn.Linear(1024, num_classes))

    def forward(self, x):
        return self.classifier(torch.flatten(self.features(x), 1))


class _TInceptionV1(tnn.Module):
    """Reference module naming (inception_v1.py:27-77) so state_dict keys
    match the published checkpoint format."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.conv7x7 = _TBasicConv(3, 64, 7, stride=2, padding=3)
        self.maxpool1 = tnn.MaxPool2d(3, 2, ceil_mode=True)
        self.lrn1 = tnn.LocalResponseNorm(64)
        self.conv1x1 = _TBasicConv(64, 64, 1)
        self.conv3x3 = _TBasicConv(64, 192, 3, padding=1)
        self.lrn2 = tnn.LocalResponseNorm(192)
        self.maxpool2 = tnn.MaxPool2d(3, 2, ceil_mode=True)
        self.inception_3a = _TInceptionModule(192, 64, 96, 128, 16, 32, 32)
        self.inception_3b = _TInceptionModule(256, 128, 128, 192, 32, 96, 64)
        self.maxpool3 = tnn.MaxPool2d(3, 2, ceil_mode=True)
        self.inception_4a = _TInceptionModule(480, 192, 96, 208, 16, 48, 64)
        self.aux1 = _TAux(512, num_classes)
        self.inception_4b = _TInceptionModule(512, 160, 112, 224, 24, 64, 64)
        self.inception_4c = _TInceptionModule(512, 128, 128, 256, 24, 64, 64)
        self.inception_4d = _TInceptionModule(512, 112, 144, 288, 32, 64, 64)
        self.aux2 = _TAux(528, num_classes)
        self.inception_4e = _TInceptionModule(528, 256, 160, 320, 32, 128, 128)
        self.maxpool4 = tnn.MaxPool2d(3, 2, ceil_mode=True)
        self.inception_5a = _TInceptionModule(832, 256, 160, 320, 32, 128, 128)
        self.inception_5b = _TInceptionModule(832, 384, 192, 384, 48, 128, 128)
        self.avgpool = tnn.AvgPool2d(7, stride=1)
        self.dropout = tnn.Dropout(0.4)
        self.linear = tnn.Linear(1024, num_classes)

    def stem_to_4a(self, x):
        x = self.lrn1(self.maxpool1(self.conv7x7(x)))
        x = self.maxpool2(self.lrn2(self.conv3x3(self.conv1x1(x))))
        x = self.inception_3b(self.inception_3a(x))
        return self.inception_4a(self.maxpool3(x))

    def forward(self, x):
        x = self.stem_to_4a(x)
        x = self.inception_4d(self.inception_4c(self.inception_4b(x)))
        x = self.maxpool4(self.inception_4e(x))
        x = self.avgpool(self.inception_5b(self.inception_5a(x)))
        return self.linear(self.dropout(torch.flatten(x, 1)))


@pytest.mark.slow
def test_inception_v1_import_forward_parity():
    from deep_vision_tpu.models.inception import AuxClassifier, InceptionV1

    gen = torch.Generator().manual_seed(16)
    net = _TInceptionV1(num_classes=1000)
    _fill(net, gen)
    imported = import_torch_inception_v1(net.state_dict())
    x = _parity(net, imported, InceptionV1(num_classes=1000), 224, gen=gen)

    # the eval graph drops aux heads on both sides, so verify their import
    # directly: feed the torch 4a feature map through the flax AuxClassifier
    with torch.no_grad():
        feat = net.stem_to_4a(x)
        ref_aux = net.aux1(feat).numpy()
    out_aux = AuxClassifier(num_classes=1000).apply(
        {"params": imported["params"]["AuxClassifier_0"]},
        jnp.asarray(feat.numpy().transpose(0, 2, 3, 1)), train=False)
    np.testing.assert_allclose(np.asarray(out_aux), ref_aux,
                               atol=2e-4, rtol=1e-3)


# ------------------------------------------------------ CLI eval harness

@pytest.mark.slow
def test_eval_pretrained_lenet_harness(tmp_path, capsys):
    """`infer eval --pretrained` must accept the non-ResNet arches too —
    the command docs/ACCURACY.md pairs with each published number.  LeNet's
    published setting IS 10-class, so the checkpoint head must be kept
    (the old num_classes==1000 heuristic would have dropped it)."""
    from deep_vision_tpu.cli import infer

    gen = torch.Generator().manual_seed(17)
    net = _torch_lenet5()
    _fill(net, gen, scale=0.2)
    pth = tmp_path / "lenet.pth"
    torch.save(net.state_dict(), pth)
    infer.main(["eval", "-m", "lenet5", "--workdir", str(tmp_path / "w"),
                "--pretrained", str(pth), "--synthetic",
                "--synthetic-size", "8", "--batch-size", "8"])
    out = capsys.readouterr().out
    assert "imported lenet5 weights" in out
    assert "with checkpoint head" in out
    assert "top1=" in out and "eval[" in out


def test_importer_rejects_wrong_arch():
    gen = torch.Generator().manual_seed(18)
    net = _torch_lenet5()
    _fill(net, gen)
    with pytest.raises(ValueError, match="5 convs"):
        import_torch_alexnet(net.state_dict())
    with pytest.raises(ValueError, match="not a reference-layout"):
        import_torch_mobilenet_v1(net.state_dict())


def test_sequential_importer_rejects_bn_variant():
    """A _bn checkpoint (torchvision vgg16_bn style) must be refused, not
    silently imported minus its BatchNorms (which evaluates to garbage)."""
    sd = _torch_lenet5().state_dict()
    sd["features.1.weight"] = torch.zeros(6)
    sd["features.1.bias"] = torch.zeros(6)
    sd["features.1.running_mean"] = torch.zeros(6)
    sd["features.1.running_var"] = torch.ones(6)
    with pytest.raises(ValueError, match="BatchNorm"):
        import_torch_lenet5(sd)


@pytest.mark.slow
def test_train_pretrained_accepts_zoo_arch(tmp_path, capsys):
    """cli.train --pretrained must accept the zoo arches for fine-tuning
    (it gated on the ResNet-only table before round 5)."""
    from deep_vision_tpu.cli import train as train_cli

    gen = torch.Generator().manual_seed(19)
    net = _torch_lenet5()
    _fill(net, gen, scale=0.2)
    pth = tmp_path / "lenet.pth"
    torch.save(net.state_dict(), pth)
    train_cli.main(["-m", "lenet5", "--synthetic", "--synthetic-size", "16",
                    "--batch-size", "8", "--epochs", "1",
                    "--workdir", str(tmp_path / "w"),
                    "--pretrained", str(pth)])
    out = capsys.readouterr().out
    assert "[pretrained] loaded lenet5 weights" in out
    assert "head kept" in out
