"""`make serve-smoke`: boot the real HTTP server wiring on a random port
against a LeNet/MNIST workdir fixture, issue one /v1/classify request,
assert a 200 — once on the synchronous path (pipeline_depth=1), once on
the pipelined executor (depth=2, the production default; asserting the
scatter did exactly one bulk D2H per batch), once with an injected
transient compute failure (the request must still answer 200 through
bisect-retry and deep health must settle back to OK), once with the
full production wire (uint8 images + bfloat16 compute) through the same
fault, and finally the multi-device pass in a fresh subprocess with 2
forced host devices (`make serve-multi` runs just that pass): a
2-replica engine at depth 2, uint8 wire + bf16 compute, with the same
injected fault — requests spread over both replicas, routing/health
surface per-replica state, still 200s throughout.
Exercises exactly the `python -m deep_vision_tpu.cli.serve` path
(cli.serve.build_server), just without serve_forever in the foreground —
run directly, not under pytest."""

import argparse
import json
import os
import sys
import tempfile
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/serve_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def smoke_one(pipeline_depth: int, faults: str = "",
              serve_devices: int = 1, requests: int = 1,
              wire_dtype: str = "uint8",
              infer_dtype: str = "float32") -> None:
    from deep_vision_tpu.cli.serve import build_server

    with tempfile.TemporaryDirectory() as workdir:
        # empty LeNet workdir fixture: restore falls back to random init,
        # which is the documented no-checkpoint smoke path
        args = argparse.Namespace(
            model="lenet5", workdir=workdir, stablehlo=None,
            host="127.0.0.1", port=0, max_batch=4, max_wait_ms=2.0,
            buckets=None, max_queue=64, warmup=False, verbose=False,
            pipeline_depth=pipeline_depth, faults=faults, fault_seed=0,
            serve_devices=serve_devices, shard_batches=False,
            wire_dtype=wire_dtype, infer_dtype=infer_dtype)
        engine, server = build_server(args)
        server.start_background()
        base = f"http://{server.host}:{server.port}"
        try:
            with urllib.request.urlopen(base + "/v1/healthz",
                                        timeout=60) as r:
                health = json.loads(r.read())
                assert r.status == 200 and health["status"] == "ok", health
                rep = health["engines"]["lenet5"]
                assert rep["batcher_alive"] and rep["accepting"], rep
                if serve_devices > 1:
                    assert len(rep["replicas"]) == serve_devices, rep
                    assert rep["can_serve"], rep
            # raw [0, 255] pixels on the uint8 wire (ints on the wire);
            # host-normalized floats on the legacy float32 wire
            if wire_dtype == "uint8":
                pixels = np.random.default_rng(0).integers(
                    0, 256, (32, 32, 1)).tolist()
            else:
                pixels = np.zeros((32, 32, 1)).tolist()
            body = json.dumps({"pixels": pixels}).encode()
            for _ in range(requests):
                req = urllib.request.Request(
                    base + "/v1/classify", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as r:
                    assert r.status == 200, f"expected 200, got {r.status}"
                    top = json.loads(r.read())["top"]
                    assert len(top) == 5, top
            with urllib.request.urlopen(base + "/v1/stats",
                                        timeout=60) as r:
                stats = json.loads(r.read())["lenet5"]
            pipe = stats["pipeline"]
            assert pipe["depth"] == pipeline_depth, pipe
            # the scatter contract: ONE bulk D2H per executed batch
            assert pipe["bulk_transfers"] == stats["batches"] >= 1, pipe
            # the wire contract: images staged/transferred in the wire
            # dtype, computed in the infer dtype, H2D bytes accounted
            assert stats["wire_dtype"] == wire_dtype, stats["wire_dtype"]
            assert stats["infer_dtype"] == infer_dtype, stats["infer_dtype"]
            assert pipe["staging"]["dtype"] == wire_dtype, pipe["staging"]
            assert pipe["h2d_transfers"] >= stats["batches"], pipe
            px_bytes = 32 * 32 * (1 if wire_dtype == "uint8" else 4)
            assert pipe["h2d_bytes"] >= pipe["h2d_transfers"] * px_bytes, pipe
            health = stats["health"]
            assert health["state"] == "ok", health
            if faults:
                # the injected failure actually fired AND was recovered
                # from (bisect-retry re-executed the cohort)
                assert health["batch_failures"] >= 1, health
                assert health["retry_executions"] >= 1, health
                assert health["faults"]["injected"], health
            extra = ""
            if serve_devices > 1:
                routed = [r["routed_batches"] for r in stats["replicas"]]
                # round-robin tie-break: sequential singles must spread
                assert all(n >= 1 for n in routed), stats["replicas"]
                assert stats["routing"]["replicas"] == serve_devices
                assert stats["admission"]["free_replicas"] \
                    == serve_devices, stats["admission"]
                extra = f", {serve_devices} replicas routed {routed}"
            print(f"serve-smoke PASS (pipeline_depth={pipeline_depth}, "
                  f"wire={wire_dtype}, infer={infer_dtype}"
                  + (f", faults='{faults}'" if faults else "") + "): "
                  f"200 from port {server.port}, top-1 class "
                  f"{top[0]['class']}, {pipe['bulk_transfers']} bulk "
                  f"transfer(s) for {stats['batches']} batch(es), "
                  f"{pipe['h2d_bytes']} H2D byte(s), "
                  f"health {health['state']}{extra}")
        finally:
            server.shutdown()
            engine.stop(drain_deadline=5.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--multi", action="store_true",
                   help="run only the multi-device pass (needs "
                        "XLA_FLAGS=--xla_force_host_platform_device_"
                        "count=2 before jax initializes; make "
                        "serve-multi sets it)")
    opts = p.parse_args()
    if opts.multi:
        # 2 fake host devices, depth 2, fault-injected: the replica
        # wiring end to end.  The platform pin must land before the jax
        # backend initializes (env JAX_PLATFORMS alone can be overridden
        # by site config, so pin at the config level too).
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=2")
        import jax

        jax.config.update("jax_platforms", "cpu")
        # the production wire: uint8 images, bf16 matmuls, f32 outputs —
        # replicated over both devices with an injected fault
        smoke_one(2, faults="compute:exception:times=1",
                  serve_devices=2, requests=6,
                  wire_dtype="uint8", infer_dtype="bfloat16")
        return 0
    # legacy float32 wire still serves (back-compat path)
    smoke_one(1, wire_dtype="float32")
    # production default: uint8 wire, device-side preprocessing
    smoke_one(2)
    # fault-injected pass: one transient compute failure — the request
    # must still answer 200 (bisect-retry), health must settle back OK
    smoke_one(2, faults="compute:exception:times=1")
    # uint8 wire + bfloat16 compute together, through the same fault —
    # the retry path must re-stage the uint8 cohort and still answer 200
    smoke_one(2, faults="compute:exception:times=1",
              wire_dtype="uint8", infer_dtype="bfloat16")
    # multi-device pass: a fresh subprocess, because the forced host
    # device count must be set before this process's jax backend exists
    import subprocess

    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--multi"], env=env)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
