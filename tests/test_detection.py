"""Detection stack tests: codec roundtrip, hand-computed IoU/NMS fixtures,
label encoding, loss behavior, mAP (SURVEY §4b: numerical tests of loss and
box codecs against hand-computed fixtures)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.models.yolo import ANCHOR_MASKS, YOLO_ANCHORS
from deep_vision_tpu.ops.boxes import (
    batched_nms,
    broadcast_iou,
    nms_single,
    xywh_to_corners,
)
from deep_vision_tpu.tasks import detection as D
from deep_vision_tpu.tasks.map_eval import MeanAPEvaluator, average_precision


def test_xywh_to_corners():
    box = jnp.array([[0.5, 0.5, 0.2, 0.4]])
    out = np.asarray(xywh_to_corners(box))
    np.testing.assert_allclose(out, [[0.4, 0.3, 0.6, 0.7]], atol=1e-6)


def test_broadcast_iou_hand_fixture():
    a = jnp.array([[0.0, 0.0, 2.0, 2.0]])          # area 4
    b = jnp.array([[1.0, 1.0, 3.0, 3.0],           # inter 1, union 7
                   [0.0, 0.0, 2.0, 2.0],           # identical
                   [5.0, 5.0, 6.0, 6.0]])          # disjoint
    iou = np.asarray(broadcast_iou(a, b))
    np.testing.assert_allclose(iou, [[1 / 7, 1.0, 0.0]], atol=1e-6)


def test_decode_encode_roundtrip():
    anchors = jnp.asarray(YOLO_ANCHORS[ANCHOR_MASKS[2]])
    rng = np.random.default_rng(0)
    raw = rng.normal(0, 1, size=(2, 13, 13, 3, 85)).astype(np.float32)
    box, obj, cls = D.decode_boxes(jnp.asarray(raw), anchors)
    t_xy, t_wh = D.encode_boxes(box, anchors)
    # encode(decode(raw)) recovers sigmoid(txy) and twh
    np.testing.assert_allclose(
        np.asarray(t_xy), jax.nn.sigmoid(raw[..., 0:2]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(t_wh), raw[..., 2:4], atol=1e-4)
    assert float(obj.min()) >= 0 and float(obj.max()) <= 1


def test_nms_suppresses_overlaps():
    boxes = jnp.array([[0.0, 0.0, 1.0, 1.0],
                       [0.05, 0.0, 1.05, 1.0],   # IoU≈0.9 with box 0
                       [2.0, 2.0, 3.0, 3.0]])    # disjoint
    scores = jnp.array([0.9, 0.8, 0.7])
    idx, sel, valid = nms_single(boxes, scores, max_outputs=3,
                                 iou_threshold=0.5)
    assert valid.tolist() == [1.0, 1.0, 0.0]     # only 2 survive
    assert idx.tolist()[:2] == [0, 2]
    np.testing.assert_allclose(sel[:2], [0.9, 0.7])


def test_batched_nms_shapes():
    rng = np.random.default_rng(1)
    boxes = jnp.asarray(rng.uniform(0, 1, (4, 50, 4)).astype(np.float32))
    boxes = jnp.concatenate([boxes[..., :2],
                             boxes[..., :2] + 0.1 + boxes[..., 2:] * 0.2], -1)
    scores = jnp.asarray(rng.uniform(0, 1, (4, 50)).astype(np.float32))
    idx, sel, valid = batched_nms(boxes, scores, max_outputs=10)
    assert idx.shape == (4, 10) and valid.shape == (4, 10)


def test_batched_nms_topk_preselect_matches_exhaustive():
    """postprocess feeds NMS only the top-k scored boxes (the full N×N
    IoU matrix OOMs at 416²/batch 16); with k ≫ max_outputs the selected
    detections must be identical to exhaustive NMS."""
    rng = np.random.default_rng(7)
    N, K, TOPK = 200, 10, 50
    boxes = rng.uniform(0, 1, (N, 4)).astype(np.float32)
    boxes = np.concatenate(
        [boxes[:, :2], boxes[:, :2] + 0.05 + boxes[:, 2:] * 0.1], -1)
    scores = rng.uniform(0, 1, (N,)).astype(np.float32)

    full_idx, full_sel, full_valid = nms_single(
        jnp.asarray(boxes), jnp.asarray(scores), max_outputs=K)

    top_scores, top_idx = jax.lax.top_k(jnp.asarray(scores), TOPK)
    top_boxes = jnp.asarray(boxes)[top_idx]
    sub_idx, sub_sel, sub_valid = nms_single(top_boxes, top_scores,
                                             max_outputs=K)
    np.testing.assert_array_equal(np.asarray(full_valid),
                                  np.asarray(sub_valid))
    np.testing.assert_allclose(np.asarray(full_sel), np.asarray(sub_sel))
    # indices map back through the top-k gather
    np.testing.assert_array_equal(
        np.asarray(full_idx) * np.asarray(full_valid),
        np.asarray(top_idx)[np.asarray(sub_idx)] * np.asarray(sub_valid))


def test_postprocess_topk_equals_full_nms():
    """End-to-end: postprocess with the default top-512 preselect must
    return exactly what exhaustive NMS (pre_nms_top_k=all) returns on
    random, non-degenerate raw outputs — guards the gather wiring."""
    rng = np.random.default_rng(9)
    B = 2
    outputs = [jnp.asarray(rng.normal(size=(B, g, g, 3, 8))
                           .astype(np.float32)) for g in (8, 4, 2)]
    n_all = sum(g * g * 3 for g in (8, 4, 2))
    got = D.postprocess(outputs, 3, max_outputs=20, pre_nms_top_k=64)
    want = D.postprocess(outputs, 3, max_outputs=20, pre_nms_top_k=n_all)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-6, atol=1e-7)


def test_postprocess_real_shapes_stay_small():
    """416² COCO shapes (10,647 candidates/image): postprocess must not
    materialize the exhaustive IoU matrix — regression guard for the
    batch-16 eval OOM."""
    B = 2
    outputs = [jnp.zeros((B, g, g, 3, 85), jnp.float32)
               for g in (52, 26, 13)]
    boxes, scores, classes, valid = D.postprocess(outputs, 80)
    assert boxes.shape == (B, 100, 4) and scores.shape == (B, 100)
    mem = jax.jit(lambda o: D.postprocess(o, 80)).lower(
        outputs).compile().memory_analysis()
    if mem is not None:  # CPU backend may not report
        assert mem.temp_size_in_bytes < 512 * 2**20, mem.temp_size_in_bytes


def test_find_best_anchor():
    # exactly the largest anchor → index 8; tiny box → index 0
    wh = np.array([[373 / 416, 326 / 416], [8 / 416, 10 / 416]])
    best = D.find_best_anchor(wh)
    assert best.tolist() == [8, 0]


def test_encode_labels_places_box():
    # one box at center, size matching anchor 8 (large) → scale 2, cell (6,6)
    boxes = np.array([[0.5, 0.5, 373 / 416, 326 / 416]], np.float32)
    classes = np.array([3])
    enc = D.encode_labels(boxes, classes, num_classes=20)
    y2 = enc["y_true_2"]  # 13×13 grid
    assert y2[6, 6, 2, 4] == 1.0          # obj at anchor slot 2 (idx 8)
    assert y2[6, 6, 2, 5 + 3] == 1.0      # one-hot class
    np.testing.assert_allclose(y2[6, 6, 2, 0:4], boxes[0], atol=1e-6)
    assert enc["y_true_0"].sum() == 0 and enc["y_true_1"].sum() == 0
    assert enc["boxes_mask"].sum() == 1


def test_encode_labels_overflow_truncated_consistently():
    """>MAX_BOXES boxes: y_true positives must cover exactly the same first
    MAX_BOXES boxes as the ignore-mask list, so no positive is simultaneously
    penalized as background."""
    rng = np.random.default_rng(7)
    n = D.MAX_BOXES + 20
    xy = rng.uniform(0.2, 0.8, (n, 2)).astype(np.float32)
    wh = rng.uniform(0.05, 0.3, (n, 2)).astype(np.float32)
    boxes = np.concatenate([xy, wh], 1)
    classes = rng.integers(0, 5, n)
    enc = D.encode_labels(boxes, classes, num_classes=5)
    assert enc["boxes_mask"].sum() == D.MAX_BOXES
    # every positive cell's box must appear in the ignore-mask list
    gt_corners = enc["boxes"][enc["boxes_mask"] > 0]
    for s in range(3):
        y = enc[f"y_true_{s}"]
        pos = y[..., 4] > 0
        for b in y[pos][:, 0:4]:
            corner = np.concatenate([b[:2] - b[2:] / 2, b[:2] + b[2:] / 2])
            match = np.abs(gt_corners - corner).max(1).min()
            assert match < 1e-6


def test_yolo_loss_zero_for_perfect_prediction():
    """If raw predictions exactly re-encode the ground truth, coordinate and
    class losses vanish and obj loss is small (finite BCE saturation)."""
    num_classes = 4
    enc = D.encode_labels(
        np.array([[0.48, 0.52, 116 / 416, 90 / 416]], np.float32),
        np.array([1]), num_classes, grids=(13,),
        masks=np.array([[6, 7, 8]]))
    y_true = jnp.asarray(enc["y_true_0"])[None]
    anchors = jnp.asarray(YOLO_ANCHORS[[6, 7, 8]])
    # build raw that decodes to the truth: logit-space inversion
    t_xy, t_wh = D.encode_boxes(y_true[..., 0:4], anchors)
    eps = 1e-6
    raw_xy = jnp.log(t_xy + eps) - jnp.log(1 - t_xy + eps)  # σ⁻¹
    obj_logit = jnp.where(y_true[..., 4:5] > 0, 20.0, -20.0)
    cls_logit = jnp.where(y_true[..., 5:] > 0, 20.0, -20.0)
    raw = jnp.concatenate([raw_xy, t_wh, obj_logit, cls_logit], -1)
    total, comps = D.yolo_scale_loss(
        raw, y_true, jnp.asarray(enc["boxes"])[None],
        jnp.asarray(enc["boxes_mask"])[None], anchors)
    assert float(comps["xy"].sum()) < 1e-4
    assert float(comps["wh"].sum()) < 1e-4
    assert float(comps["class"].sum()) < 1e-3
    assert float(comps["obj"].sum()) < 1e-3
    assert float(total.sum()) < 2e-3


def test_yolo_loss_penalizes_wrong_prediction():
    num_classes = 4
    enc = D.encode_labels(
        np.array([[0.5, 0.5, 116 / 416, 90 / 416]], np.float32),
        np.array([1]), num_classes, grids=(13,), masks=np.array([[6, 7, 8]]))
    y_true = jnp.asarray(enc["y_true_0"])[None]
    anchors = jnp.asarray(YOLO_ANCHORS[[6, 7, 8]])
    raw = jnp.zeros((1, 13, 13, 3, 5 + num_classes))
    total, _ = D.yolo_scale_loss(
        raw, y_true, jnp.asarray(enc["boxes"])[None],
        jnp.asarray(enc["boxes_mask"])[None], anchors)
    assert float(total.sum()) > 1.0


def test_yolo_loss_grad_with_pallas_path():
    """value_and_grad must work through the Pallas ignore-mask path —
    pallas_call has no autodiff rule, so the mask is stop_gradient'd."""
    num_classes = 3
    enc = D.encode_labels(
        np.array([[0.5, 0.5, 0.3, 0.3]], np.float32),
        np.array([1]), num_classes, grids=(13,), masks=np.array([[6, 7, 8]]))
    y_true = jnp.asarray(enc["y_true_0"])[None]
    anchors = jnp.asarray(YOLO_ANCHORS[[6, 7, 8]])
    raw = jnp.zeros((1, 13, 13, 3, 5 + num_classes))

    def loss_fn(raw):
        total, _ = D.yolo_scale_loss(
            raw, y_true, jnp.asarray(enc["boxes"])[None],
            jnp.asarray(enc["boxes_mask"])[None], anchors, use_pallas=True)
        return total.sum()

    loss, grads = jax.value_and_grad(loss_fn)(raw)
    assert np.isfinite(float(loss))
    assert np.isfinite(np.asarray(grads)).all()
    assert float(jnp.abs(grads).max()) > 0


def test_average_precision_perfect():
    r = np.array([0.5, 1.0])
    p = np.array([1.0, 1.0])
    assert average_precision(r, p) == pytest.approx(1.0)
    assert average_precision(r, p, use_07_metric=True) == pytest.approx(1.0, abs=0.1)


def test_map_evaluator_perfect_and_miss():
    ev = MeanAPEvaluator(num_classes=2)
    gt = np.array([[0.0, 0.0, 1.0, 1.0]])
    ev.add(gt, np.array([0.9]), np.array([0]), gt, np.array([0]))
    # second image: class 1 gt, detection misses (disjoint box)
    ev.add(np.array([[5, 5, 6, 6.0]]), np.array([0.8]), np.array([1]),
           np.array([[0.0, 0.0, 1.0, 1.0]]), np.array([1]))
    res = ev.compute()
    assert res["per_class"][0] == pytest.approx(1.0)
    assert res["per_class"][1] == pytest.approx(0.0)
    assert res["mAP"] == pytest.approx(0.5)
    # exact hit scores 1.0 at every COCO threshold; the miss 0 at every one
    assert res["mAP50_95"] == pytest.approx(0.5)


def test_map_coco_average_partial_overlap():
    """A detection at IoU 0.8 passes thresholds 0.50–0.80 (7 of the 10 COCO
    grid points) and fails 0.85–0.95 → mAP50_95 = 0.7 while mAP@0.5 = 1."""
    ev = MeanAPEvaluator(num_classes=1)
    gt = np.array([[0.0, 0.0, 10.0, 10.0]])
    det = np.array([[0.0, 0.0, 10.0, 8.0]])   # inter 80 / union 100 = 0.8
    ev.add(det, np.array([0.9]), np.array([0]), gt, np.array([0]))
    res = ev.compute()
    assert res["mAP"] == pytest.approx(1.0)
    assert res["mAP50_95"] == pytest.approx(0.7)


def test_map_boundary_iou_counts_as_matched():
    """A detection EXACTLY on a COCO grid threshold matches at that
    threshold by construction (IOU_EPS comparison slack), independent of
    how the grid doubles were produced — previously this held only
    because np.arange(...).round(2) and the IoU arithmetic happened to
    round to the same nearest doubles."""
    ev = MeanAPEvaluator(num_classes=1)
    for thr in MeanAPEvaluator.COCO_IOUS:
        # gt 10×10 at origin; det [0,0,10,10t] nests inside it, so
        # union = gt area and IoU = inter/union = 100t/100 = exactly t
        ev.add(np.array([[0.0, 0.0, 10.0, 10.0 * thr]]), np.array([0.9]),
               np.array([0]), np.array([[0.0, 0.0, 10.0, 10.0]]),
               np.array([0]))
    res = ev.compute()
    # image k's IoU is grid point k: it matches thresholds 0..k, so
    # mAP50_95 = mean over thresholds of AP with (10−k)/10 recall ...
    # the key regression signal is the primary threshold: every image
    # with IoU ≥ 0.5 (all 10) must match at 0.5 despite 5 of them
    # sitting exactly ON a grid value
    assert res["mAP"] == pytest.approx(1.0)
    assert res["mAP50_95"] > 0.0


def test_map_matching_rules_crowded_objects():
    """The two matching rules diverge on crowded scenes, and each metric
    uses its own: det2's argmax-IoU gt is taken by det1, so VOC-devkit
    matching (mAP@0.5 — comparable to published VOC numbers) counts it
    FP (AP 0.5), while COCO matching (the mAP50_95 grid) lets it fall
    through to the unmatched gt above threshold (AP 1.0 at IoUs ≤ 0.8)."""
    ev = MeanAPEvaluator(num_classes=1)
    gts = np.array([[0.0, 0.0, 10.0, 10.0], [2.0, 0.0, 12.0, 10.0]])
    dets = np.array([[0.0, 0.0, 10.0, 10.0],   # IoU 1.0 / 0.667
                     [1.0, 0.0, 11.0, 10.0]])  # IoU 0.818 / 0.818 (tie)
    ev.add(dets, np.array([0.9, 0.8]), np.array([0, 0]),
           gts, np.array([0, 0]))
    res = ev.compute()
    assert res["mAP"] == pytest.approx(0.5)       # VOC rule: det2 is FP
    # COCO rule: both match for the 7 grid points ≤0.80 where det2's 0.818
    # clears threshold (AP 1.0); above that only det1 matches.  AP at a
    # threshold where recall stops at 0.5 with precision 1.0 is 0.5, so
    # the average is (7·1.0 + 3·0.5)/10
    assert res["mAP50_95"] == pytest.approx(0.85)


def test_yolov3_model_shapes():
    from deep_vision_tpu.models.yolo import YoloV3

    model = YoloV3(num_classes=20)
    x = jnp.zeros((1, 128, 128, 3))
    variables = jax.eval_shape(
        lambda a: model.init({"params": jax.random.PRNGKey(0)}, a,
                             train=False), x)
    outs = jax.eval_shape(
        lambda v, a: model.apply(v, a, train=False), variables, x)
    assert outs[0].shape == (1, 16, 16, 3, 25)   # large grid (÷8)
    assert outs[1].shape == (1, 8, 8, 3, 25)
    assert outs[2].shape == (1, 4, 4, 3, 25)
    from deep_vision_tpu.models.common import count_params

    n = sum(x.size for x in jax.tree_util.tree_leaves(variables["params"]))
    assert 61_000_000 < n < 63_000_000  # canonical yolov3-coco≈62M (here C=20)
