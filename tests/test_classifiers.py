"""Shape/param/behavior tests for the classifier zoo (SURVEY §4a: the
reference's model.summary()/torchsummary printouts are the spec)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.models import (
    AlexNetV1,
    AlexNetV2,
    InceptionV1,
    InceptionV3,
    MobileNetV1,
    ShuffleNetV1,
    VGG16,
    VGG19,
)
from deep_vision_tpu.models.common import count_params, local_response_norm


def _init_apply(model, size, train=False, num_out=10):
    x = jax.random.normal(jax.random.PRNGKey(1), (2, size, size, 3))
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    rngs = {"dropout": jax.random.PRNGKey(2)} if train else None
    kwargs = dict(rngs=rngs) if train else {}
    mutable = ["batch_stats"] if "batch_stats" in variables else False
    out = model.apply(variables, x, train=train, mutable=mutable, **kwargs)
    if mutable:
        out, _ = out
    return variables, out


def _shape_count(model, size):
    # eval_shape: param arithmetic without compiling the init program
    v = jax.eval_shape(
        lambda x: model.init({"params": jax.random.PRNGKey(0)}, x,
                             train=False),
        jnp.zeros((1, size, size, 3)))
    return count_params(v["params"])


# goldens: VGG/MobileNet/InceptionV3 match the canonical models exactly;
# AlexNets follow the reference's filter plans (V1 one-tower 96/256/...,
# V2 "one weird trick" 64/192/384/384/256 — NOT torchvision's 256-conv4)
@pytest.mark.parametrize("ctor,size,expected", [
    (VGG16, 224, 138_357_544),
    (VGG19, 224, 143_667_240),
    (AlexNetV1, 224, 62_378_344),
    (AlexNetV2, 224, 61_838_248),
    (InceptionV1, 224, 13_378_280),  # incl. both aux heads
    (MobileNetV1, 224, 4_231_976),
    (ShuffleNetV1, 224, 1_865_728),
    (InceptionV3, 299, 27_161_264),  # == torchvision inception_v3
])
def test_param_counts(ctor, size, expected):
    assert _shape_count(ctor(), size) == expected


@pytest.mark.parametrize("ctor,size", [
    (AlexNetV1, 96), (AlexNetV2, 96), (VGG16, 64),
    (MobileNetV1, 64),
    # ShuffleNet's grouped convs are the slowest classifier compile on a
    # 1-core host; its forward check rides the slow lane
    pytest.param(ShuffleNetV1, 64, marks=pytest.mark.slow),
])
def test_eval_forward_shape(ctor, size):
    _, out = _init_apply(ctor(num_classes=10), size)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


@pytest.mark.slow
def test_inception_v1_aux_heads_train_only():
    model = InceptionV1(num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 128, 3))
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)  # eval: single head
    outs = model.apply(variables, x, train=True,
                       rngs={"dropout": jax.random.PRNGKey(2)})
    assert isinstance(outs, tuple) and len(outs) == 3  # main + 2 aux
    assert all(o.shape == (2, 10) for o in outs)


@pytest.mark.slow
def test_inception_v3_aux_head_train_only():
    model = InceptionV3(num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 299, 299, 3))
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    out, _ = model.apply(variables, x, train=True, mutable=["batch_stats"],
                         rngs={"dropout": jax.random.PRNGKey(2)})
    assert isinstance(out, tuple) and len(out) == 2
    assert out[0].shape == (1, 10) and out[1].shape == (1, 10)


def test_mobilenet_alpha_scales_width():
    nb = _shape_count(MobileNetV1(alpha=1.0), 64)
    ns = _shape_count(MobileNetV1(alpha=0.5), 64)
    assert ns < 0.45 * nb


def test_shufflenet_channel_shuffle_is_permutation():
    from deep_vision_tpu.models.shufflenet import channel_shuffle

    x = jnp.arange(12, dtype=jnp.float32).reshape(1, 1, 1, 12)
    y = channel_shuffle(x, 3)
    assert sorted(np.asarray(y).ravel().tolist()) == list(range(12))
    # groups interleave: [0,4,8, 1,5,9, ...]
    assert np.asarray(y).ravel()[:3].tolist() == [0.0, 4.0, 8.0]


def test_lrn_matches_torch():
    torch = pytest.importorskip("torch")

    x = np.random.default_rng(0).normal(size=(2, 7, 7, 6)).astype(np.float32)
    ours = np.asarray(local_response_norm(jnp.asarray(x), size=5))
    xt = torch.from_numpy(x).permute(0, 3, 1, 2)  # NHWC→NCHW
    ref = torch.nn.LocalResponseNorm(5)(xt).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)
