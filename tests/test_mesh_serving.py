"""2-D ``data × model`` mesh serving under forced host devices (conftest
pins 8 virtual CPU devices): the regex partition-rule engine
(parallel/partition.py) — first-match-wins, strict exactly-one-match,
the first-divisible-axis fallback and its indivisible-trailing-dim fix —
then the serving path end to end: every mesh cell (2×2, 4×1, 1×4) must
produce outputs allclose to the single-device engine with bit-identical
top-1, bucket divisibility errors must name both mesh axes, per-chip
``param_bytes()`` must price one chip's shard (strictly below the
replicated footprint when the model axis is real), and the weight cache
must spill/re-admit a model-sharded view bit-identically with zero
recompiles.  Correctness only — the 8 "devices" share one host;
bench.py --serve-mesh measures the actual cells."""

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deep_vision_tpu.parallel.mesh import make_mesh
from deep_vision_tpu.parallel.partition import (
    first_divisible_spec,
    leaf_paths,
    match_partition_rules,
    parse_partition_rules,
    RULE_TABLES,
)
from deep_vision_tpu.serve.engine import BatchingEngine, sharded_buckets
from deep_vision_tpu.serve.models import WeightCache
from deep_vision_tpu.serve.registry import ModelRegistry

pytestmark = [pytest.mark.serve, pytest.mark.mesh]

# disjoint (strict-compatible) table for the LeNet fixture: the wide
# leaves shard over ``model``, everything else replicates explicitly
LENET_STRICT_RULES = [
    (r"Conv_2/kernel$", P(None, None, None, "model")),
    (r"Dense_0/kernel$", P(None, "model")),
    (r"(bias|Conv_[01]/kernel|Dense_1/kernel)$", P()),
]


@pytest.fixture(scope="module")
def lenet_serving(tmp_path_factory):
    reg = ModelRegistry()
    # empty workdir fixture → deterministic PRNGKey(0) random init
    sm = reg.load_checkpoint(
        "lenet5", str(tmp_path_factory.mktemp("mesh_workdir")))
    return reg, sm


def _images(n, shape=(32, 32, 1)):
    return [np.random.RandomState(i).randn(*shape).astype(np.float32)
            for i in range(n)]


def _mesh(host_devices, d, m):
    return make_mesh({"data": d, "model": m},
                     devices=host_devices[:d * m])


# -- the rule engine -------------------------------------------------------


def test_match_rules_first_wins_and_unmatched_replicates():
    params = {"params": {"head": {"kernel": np.zeros((8, 4)),
                                  "bias": np.zeros((4,))},
                         "step": np.zeros(())}}
    specs = match_partition_rules(
        [(r"head/kernel$", P(None, "model")),
         (r"head/.*", P("model"))], params)
    assert specs["params"]["head"]["kernel"] == P(None, "model")  # first
    assert specs["params"]["head"]["bias"] == P("model")
    assert specs["params"]["step"] == P()  # scalar: always replicated


def test_strict_rejects_unmatched_and_overlap():
    params = {"head": {"kernel": np.zeros((8, 4)),
                       "bias": np.zeros((4,))}}
    with pytest.raises(ValueError, match="matches no rule"):
        match_partition_rules([(r"kernel$", P(None, "model"))],
                              params, strict=True)
    with pytest.raises(ValueError, match="matches 2 rules"):
        match_partition_rules([(r"kernel$", P(None, "model")),
                               (r".*", P())], params, strict=True)
    # a disjoint table passes
    specs = match_partition_rules([(r"kernel$", P(None, "model")),
                                   (r"bias$", P())], params, strict=True)
    assert specs["head"]["kernel"] == P(None, "model")


def test_builtin_tables_are_first_match_non_strict():
    params = {"params": {"head": {"kernel": np.zeros((128, 1000))}}}
    specs = match_partition_rules(RULE_TABLES["classifier"], params)
    assert specs["params"]["head"]["kernel"] == P(None, "model")
    # the catch-all overlaps every specific rule, so strict (exactly
    # one match) rejects the built-in tables by construction
    with pytest.raises(ValueError, match="matches 2 rules"):
        match_partition_rules(RULE_TABLES["classifier"], params,
                              strict=True)


def test_first_divisible_skips_indivisible_trailing_dim():
    """The silent-replication fix: a leaf whose TRAILING dim is wide
    but indivisible used to replicate wholesale; now an earlier
    divisible dim is sharded instead."""
    # 1002 % 4 != 0 → the old sharder replicated; dim 0 (2048) shards
    assert first_divisible_spec((2048, 1002), 4, min_shard_dim=512) \
        == P("model", None)
    # trailing dim divisible → it keeps priority
    assert first_divisible_spec((2048, 1024), 4, min_shard_dim=512) \
        == P(None, "model")
    # nothing qualifies → replicate
    assert first_divisible_spec((100, 100), 4, min_shard_dim=512) == P()
    assert first_divisible_spec((2048, 1024), 1) == P()  # no model axis


def test_parse_partition_rules_inline_and_table():
    assert parse_partition_rules("classifier") \
        == RULE_TABLES["classifier"]
    rules = parse_partition_rules("head/kernel=-,model;.*=")
    assert rules == [("head/kernel", P(None, "model")), (".*", P())]
    with pytest.raises(ValueError, match="regex=axes"):
        parse_partition_rules("no-equals-sign-here")


def test_leaf_paths_join_with_slash(lenet_serving):
    _, sm = lenet_serving
    names = [n for n, _ in leaf_paths(sm._variables)]
    assert "params/Conv_0/kernel" in names
    assert "params/Dense_1/bias" in names


# -- the serving path ------------------------------------------------------


@pytest.mark.parametrize("d,m", [(2, 2), (4, 1), (1, 4)],
                         ids=["2x2", "4x1", "1x4"])
def test_mesh_cells_match_single_device(lenet_serving, host_devices,
                                        d, m):
    """Every mesh cell serves outputs allclose to the single-device
    engine, with bit-identical top-1 — GSPMD's collectives are a layout
    detail, never a numerics change the client can see."""
    _, sm = lenet_serving
    imgs = _images(8)
    with BatchingEngine(sm, max_batch=4, max_wait_ms=1.0) as ref_eng:
        ref = [np.asarray(ref_eng.infer(x, timeout=60)) for x in imgs]
    view = sm.for_mesh(_mesh(host_devices, d, m), min_shard_dim=64)
    with BatchingEngine(view, max_batch=4, max_wait_ms=1.0,
                        buckets=sharded_buckets(4, d)) as eng:
        got = [np.asarray(eng.infer(x, timeout=60)) for x in imgs]
        st = eng.stats()
    assert st["mesh_shape"] == {"data": d, "model": m}
    for r, g in zip(ref, got):
        np.testing.assert_allclose(r, g, rtol=1e-5, atol=1e-5)
        assert int(np.argmax(r)) == int(np.argmax(g))  # top-1 identical


def test_strict_rules_through_for_mesh(lenet_serving, host_devices):
    _, sm = lenet_serving
    mesh = _mesh(host_devices, 2, 2)
    # a disjoint table passes strict and actually shards
    view = sm.for_mesh(mesh, partition_rules=LENET_STRICT_RULES,
                       strict=True, min_shard_dim=64)
    assert view.param_bytes() < view.param_global_bytes()
    # a table that misses leaves fails loudly at load
    with pytest.raises(ValueError, match="matches no rule"):
        sm.for_mesh(mesh, partition_rules=[
            (r"Dense_0/kernel$", P(None, "model"))], strict=True)


def test_divisibility_error_names_both_axes(lenet_serving,
                                            host_devices):
    _, sm = lenet_serving
    view = sm.for_mesh(_mesh(host_devices, 2, 2), min_shard_dim=64)
    with pytest.raises(ValueError) as e:
        view.compile_bucket(3)
    msg = str(e.value)
    assert "2×2 data×model mesh" in msg
    assert "nearest usable bucket is 4" in msg
    assert "multiples of 2" in msg


def test_per_chip_bytes_below_replicated_on_1x4(lenet_serving,
                                                host_devices):
    """The HBM contract: a real model axis must price each chip at its
    addressable shard, strictly below the replicated footprint, while
    the logical size is unchanged."""
    _, sm = lenet_serving
    replicated = sm.param_bytes()
    view = sm.for_mesh(_mesh(host_devices, 1, 4), min_shard_dim=64)
    assert view.mesh_shape() == {"data": 1, "model": 4}
    assert view.param_bytes() < replicated
    assert view.param_global_bytes() == replicated
    # a pure data mesh replicates params: per-chip == global, as before
    flat = sm.for_mesh(_mesh(host_devices, 4, 1), min_shard_dim=64)
    assert flat.param_bytes() == replicated


def test_cache_spill_readmit_sharded_bit_identical(lenet_serving,
                                                   host_devices):
    """Evict→spill→re-admit of a model-sharded view: the spill gathers
    shards into full host values, re-admit lands them back under the
    view's sharding pytree — outputs bit-identical, zero recompiles,
    and the re-admitted leaves still price per-chip."""
    reg, sm = lenet_serving
    view = sm.for_mesh(_mesh(host_devices, 2, 2), min_shard_dim=64)
    # budget holds exactly one model: registering the view evicts sm
    cache = WeightCache(budget_bytes=sm.param_bytes() + 1)
    cache.register(sm)
    cache.register(view)
    img = _images(1)[0]
    with BatchingEngine(view, max_batch=4, max_wait_ms=1.0,
                        buckets=sharded_buckets(4, 2)) as eng:
        first = np.asarray(eng.infer(img, timeout=60))
        compiles = eng.compiles
        # touching sm admits it, evicting the view (the LRU resident);
        # the spill device_gets every sharded leaf to its full value
        assert cache.variables_for(sm) is not None
        assert not cache._entries[id(view)]["resident"]
        # next batch re-admits through _live_variables: device_put
        # against the sharding pytree, no compile
        again = np.asarray(eng.infer(img, timeout=60))
        assert np.array_equal(first, again)  # bit-identical round trip
        assert eng.compiles == compiles
    assert view.param_bytes() < view.param_global_bytes()
    st = cache.stats()
    assert st["evictions"] >= 1 and st["spilled_bytes_total"] > 0


# -- 2-process pod ---------------------------------------------------------


@pytest.mark.slow
def test_mesh_serving_two_processes(tmp_path):
    """A real 2-process pod (2 virtual devices each) serving over a 2×2
    data×model mesh: every addressable output shard matches a local
    single-device reference on each rank, per-chip bytes price below
    the replicated footprint, and both ranks report identical RESULTs
    (tests/dist_mesh_worker.py)."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "dist_mesh_worker.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, str(pid), "2", str(tmp_path)],
        env=env, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    if any("SKIPBACKEND" in out for out in outs):
        pytest.skip("jaxlib CPU backend lacks multiprocess SPMD "
                    "(needs a pod or a collectives-capable backend)")
    results = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out}"
        line = [ln for ln in out.splitlines()
                if ln.startswith(f"RESULT pid={pid}")]
        assert line, out
        results.append(line[0].split(f"RESULT pid={pid} ")[1])
    # same weights, same batch → byte-identical payloads across ranks
    assert results[0] == results[1], results
