"""Pallas kernel numerics vs the XLA reference implementation."""

import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.ops.boxes import broadcast_iou
from deep_vision_tpu.ops.pallas_ops import best_iou_max


def _reference(pred, gt, mask):
    iou = broadcast_iou(pred, gt)
    iou = jnp.where(mask[:, None, :] > 0, iou, 0.0)
    return iou.max(-1)


def test_best_iou_max_matches_reference():
    rng = np.random.default_rng(0)
    B, N, M = 2, 700, 100  # N not a tile multiple, M not lane multiple
    p1 = rng.uniform(0, 0.8, (B, N, 2)).astype(np.float32)
    pred = np.concatenate([p1, p1 + rng.uniform(0.05, 0.2, (B, N, 2))
                           .astype(np.float32)], -1)
    g1 = rng.uniform(0, 0.8, (B, M, 2)).astype(np.float32)
    gt = np.concatenate([g1, g1 + rng.uniform(0.05, 0.2, (B, M, 2))
                         .astype(np.float32)], -1)
    mask = (rng.uniform(size=(B, M)) > 0.5).astype(np.float32)
    got = best_iou_max(jnp.asarray(pred), jnp.asarray(gt),
                       jnp.asarray(mask), interpret=True)
    want = _reference(jnp.asarray(pred), jnp.asarray(gt), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_best_iou_max_all_masked_is_zero():
    pred = jnp.asarray(np.random.default_rng(1)
                       .uniform(0, 1, (1, 64, 4)).astype(np.float32))
    gt = jnp.zeros((1, 8, 4))
    mask = jnp.zeros((1, 8))
    out = best_iou_max(pred, gt, mask, interpret=True)
    assert float(jnp.abs(out).max()) == 0.0


def test_parity_check_passes_interpret():
    """The startup gate the CLI uses before enabling the Pallas path."""
    from deep_vision_tpu.ops.pallas_ops import pallas_parity_ok

    assert pallas_parity_ok(interpret=True)


def test_best_iou_max_sharded_matches_reference(mesh8):
    """The data-axis shard_map wrapper (the multi-chip path for the fused
    kernel) reproduces the XLA reference on an 8-device mesh."""
    from deep_vision_tpu.ops.pallas_ops import best_iou_max_sharded

    rng = np.random.default_rng(2)
    B, N, M = 16, 300, 40  # 2 images per shard
    p1 = rng.uniform(0, 0.8, (B, N, 2)).astype(np.float32)
    pred = np.concatenate([p1, p1 + rng.uniform(0.05, 0.2, (B, N, 2))
                           .astype(np.float32)], -1)
    g1 = rng.uniform(0, 0.8, (B, M, 2)).astype(np.float32)
    gt = np.concatenate([g1, g1 + rng.uniform(0.05, 0.2, (B, M, 2))
                         .astype(np.float32)], -1)
    mask = (rng.uniform(size=(B, M)) > 0.5).astype(np.float32)
    got = best_iou_max_sharded(jnp.asarray(pred), jnp.asarray(gt),
                               jnp.asarray(mask), mesh8)
    want = _reference(jnp.asarray(pred), jnp.asarray(gt), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
