"""Shape/param-count golden tests (SURVEY §4: the reference's torchsummary
printouts are the spec)."""

import jax
import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.models.common import count_params
from deep_vision_tpu.models.lenet import LeNet5


def test_lenet5_shapes_and_params():
    model = LeNet5()
    x = jnp.zeros((2, 32, 32, 1))
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)
    assert out.shape == (2, 10)
    # classic LeNet-5: 156 + 2416 + 48120 + 10164 + 850
    assert count_params(variables["params"]) == 61_706


def test_lenet5_deterministic():
    model = LeNet5()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 32, 32, 1)),
                    jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    a = model.apply(variables, x)
    b = model.apply(variables, x)
    np.testing.assert_allclose(a, b)
