"""Pose stack tests: heatmap codec fixtures, hourglass shapes, loss,
crop_roi, PCKh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.data.pose import PoseLoader, crop_roi, synthetic_pose_dataset
from deep_vision_tpu.models.hourglass import StackedHourglass
from deep_vision_tpu.tasks.pose import (
    PoseTask,
    heatmap_argmax,
    make_heatmaps,
    pckh,
)


def test_heatmap_peak_and_support():
    hm = make_heatmaps(np.array([[10, 20, 2]]), 64, 64)
    assert hm.shape == (64, 64, 1)
    assert hm[20, 10, 0] == pytest.approx(12.0)       # ×12 scale at center
    assert hm[20, 11, 0] == pytest.approx(12.0 * np.exp(-0.5), rel=1e-5)
    assert hm[20, 14, 0] == 0.0                        # outside 7×7 support
    assert hm[24, 10, 0] == 0.0


def test_heatmap_invisible_and_oob_are_zero():
    hm = make_heatmaps(np.array([[10, 20, 0], [-50, -50, 2], [5, 5, 1]]),
                       64, 64)
    assert hm[..., 0].sum() == 0.0    # invisible
    assert hm[..., 1].sum() == 0.0    # out of bounds
    assert hm[..., 2].sum() > 0.0


def test_heatmap_edge_clipping():
    hm = make_heatmaps(np.array([[0, 0, 2]]), 64, 64)
    assert hm[0, 0, 0] == pytest.approx(12.0)
    assert np.isfinite(hm).all()


def test_heatmap_argmax_roundtrip():
    kp = np.array([[33, 17, 2], [5, 60, 2]])
    hm = make_heatmaps(kp, 64, 64)
    rec = heatmap_argmax(hm)
    np.testing.assert_allclose(rec, kp[:, :2], atol=0.5)


def test_pckh():
    pred = np.array([[10.0, 10.0], [50.0, 50.0]])
    true = np.array([[11.0, 10.0], [20.0, 20.0]])
    vis = np.array([1.0, 1.0])
    correct, total = pckh(pred, true, vis, head_size=5.0)
    assert (correct, total) == (1.0, 2)


def test_crop_roi_keypoints_normalized():
    img = np.zeros((200, 300, 3), np.uint8)
    kp = np.array([[100, 50, 2], [200, 150, 2], [-1, -1, 0]], np.float32)
    crop, norm = crop_roi(img, kp, scale=0.5)
    assert crop.shape[0] <= 200 and crop.shape[1] <= 300
    vis = norm[:2]
    assert (vis[:, 0] >= 0).all() and (vis[:, 0] <= 1).all()
    assert (vis[:, 1] >= 0).all() and (vis[:, 1] <= 1).all()


def test_hourglass_shapes_and_stacks():
    model = StackedHourglass(num_stack=2, num_heatmap=16, filters=64)
    x = jnp.zeros((1, 64, 64, 3))
    variables = jax.eval_shape(
        lambda a: model.init({"params": jax.random.PRNGKey(0)}, a,
                             train=False), x)
    outs = jax.eval_shape(
        lambda v, a: model.apply(v, a, train=False), variables, x)
    assert len(outs) == 2
    assert all(o.shape == (1, 16, 16, 16) for o in outs)   # ÷4 resolution
    assert all(o.dtype == jnp.float32 for o in outs)


def test_pose_loss_weights_foreground():
    task = PoseTask()
    labels = jnp.zeros((1, 8, 8, 2)).at[0, 3, 3, 0].set(12.0)
    perfect = (labels,)
    zero = (jnp.zeros_like(labels),)
    l_perfect, _ = task.loss(perfect, {"heatmaps": labels})
    l_zero, _ = task.loss(zero, {"heatmaps": labels})
    assert float(l_perfect) == 0.0
    # foreground miss is weighted 82× over a same-size background miss
    assert float(l_zero) == pytest.approx(12.0**2 * 82 / (8 * 8 * 2))


def test_pose_loader_shapes():
    samples = synthetic_pose_dataset(4, image_size=64, num_keypoints=4)
    loader = PoseLoader(samples, batch_size=2, image_size=64,
                        heatmap_size=16, num_keypoints=4)
    batch = next(iter(loader))
    assert batch["image"].shape == (2, 64, 64, 3)
    assert batch["heatmaps"].shape == (2, 16, 16, 4)
    assert batch["heatmaps"].max() <= 12.0


def test_pose_loader_pool_matches_sequential():
    """Shared PreppedSampleLoader contract: pooled and sequential pose
    iteration are byte-identical (per-item rng), flips included."""
    from deep_vision_tpu.data.pose import PoseLoader, synthetic_pose_dataset

    samples = synthetic_pose_dataset(6, image_size=64, num_keypoints=16)
    seq = PoseLoader(samples, batch_size=3, image_size=64, heatmap_size=16,
                     train=True, seed=4)
    pooled = PoseLoader(samples, batch_size=3, image_size=64,
                        heatmap_size=16, train=True, seed=4, num_workers=2)
    try:
        for a, b in zip(seq, pooled):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
    finally:
        pooled.close()


def test_pose_loader_device_normalize_parity():
    import jax.numpy as jnp

    from deep_vision_tpu.data.pose import PoseLoader, synthetic_pose_dataset
    from deep_vision_tpu.ops.preprocess import make_scale_preprocess

    samples = synthetic_pose_dataset(4, image_size=64, num_keypoints=16)
    host = PoseLoader(samples, batch_size=4, image_size=64, heatmap_size=16,
                      train=True, seed=6)
    dev = PoseLoader(samples, batch_size=4, image_size=64, heatmap_size=16,
                     train=True, seed=6, device_normalize=True)
    hb, db = next(iter(host)), next(iter(dev))
    assert db["image"].dtype == np.uint8
    out = make_scale_preprocess()({"image": jnp.asarray(db["image"])},
                                  None, True)
    np.testing.assert_allclose(np.asarray(out["image"]), hb["image"],
                               atol=1e-6)
    np.testing.assert_array_equal(hb["heatmaps"], db["heatmaps"])
