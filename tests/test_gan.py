"""GAN stack tests: model shapes, ImagePool semantics, DCGAN/CycleGAN
train steps (loss finite + params change), AdversarialTrainer smoke."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.models.gan import (
    CycleGANGenerator,
    DCGANDiscriminator,
    DCGANGenerator,
    PatchGANDiscriminator,
)
from deep_vision_tpu.tasks.gan import CycleGANTask, DCGANTask, ImagePool


def test_dcgan_generator_shape():
    g = DCGANGenerator()
    z = jnp.zeros((2, 100))
    variables = g.init({"params": jax.random.PRNGKey(0)}, z, train=False)
    out = g.apply(variables, z, train=False)
    assert out.shape == (2, 28, 28, 1)
    assert float(out.min()) >= -1.0 and float(out.max()) <= 1.0


def test_cyclegan_generator_shape_and_discriminator_patch():
    g = CycleGANGenerator(n_blocks=2)
    x = jnp.zeros((1, 64, 64, 3))
    gv = jax.eval_shape(
        lambda a: g.init({"params": jax.random.PRNGKey(0)}, a, train=False), x)
    out = jax.eval_shape(lambda v, a: g.apply(v, a, train=False), gv, x)
    assert out.shape == (1, 64, 64, 3)
    d = PatchGANDiscriminator()
    dv = jax.eval_shape(
        lambda a: d.init({"params": jax.random.PRNGKey(0)}, a, train=False), x)
    patch = jax.eval_shape(lambda v, a: d.apply(v, a, train=False), dv, x)
    assert patch.shape == (1, 8, 8, 1)  # 3 stride-2 halvings of 64


def test_image_pool_replay():
    pool = ImagePool(pool_size=4, seed=0)
    first = np.ones((4, 2, 2, 1), np.float32)
    out1 = pool.query(first)
    np.testing.assert_array_equal(out1, first)  # buffer fills, passthrough
    second = np.full((4, 2, 2, 1), 2.0, np.float32)
    out2 = pool.query(second)
    # some of the second batch should be swapped for stored ones
    assert out2.shape == first.shape
    assert (out2 == 1.0).any() or (out2 == 2.0).all()
    # pool retains exactly pool_size images
    assert len(pool.pool) == 4


def test_dcgan_train_step_updates_both_models():
    task = DCGANTask(DCGANGenerator(), DCGANDiscriminator(), latent_dim=16)
    rng = jax.random.PRNGKey(0)
    batch = {"image": jnp.asarray(
        np.random.default_rng(0).uniform(-1, 1, (4, 28, 28, 1))
        .astype(np.float32))}
    states = task.init_states(rng, batch)
    new_states, outputs, metrics = jax.jit(task.train_step)(
        states, batch, rng)
    assert np.isfinite(float(metrics["g_loss"]))
    assert np.isfinite(float(metrics["d_loss"]))
    g0 = jax.tree_util.tree_leaves(states["generator"].params)[0]
    g1 = jax.tree_util.tree_leaves(new_states["generator"].params)[0]
    assert not np.allclose(g0, g1)
    d0 = jax.tree_util.tree_leaves(states["discriminator"].params)[0]
    d1 = jax.tree_util.tree_leaves(new_states["discriminator"].params)[0]
    assert not np.allclose(d0, d1)


@pytest.mark.slow
def test_cyclegan_train_step_four_networks():
    task = CycleGANTask(lambda: CycleGANGenerator(n_blocks=1),
                        lambda: PatchGANDiscriminator(), pool_size=4)
    rng = jax.random.PRNGKey(0)
    a = np.random.default_rng(0).uniform(-1, 1, (2, 32, 32, 3)).astype(np.float32)
    b = np.random.default_rng(1).uniform(-1, 1, (2, 32, 32, 3)).astype(np.float32)
    batch = {"image_a": jnp.asarray(a), "image_b": jnp.asarray(b)}
    states = task.init_states(rng, batch)
    prepared = task.host_prepare({"image_a": a, "image_b": b})
    prepared = {k: jnp.asarray(v) for k, v in prepared.items()}
    new_states, outputs, metrics = jax.jit(task.train_step)(
        states, prepared, rng)
    for k in ("g_loss", "d_loss", "cycle", "ident"):
        assert np.isfinite(float(metrics[k])), k
    assert outputs["fake_a2b"].shape == (2, 32, 32, 3)
    for name in states:
        p0 = jax.tree_util.tree_leaves(states[name].params)[0]
        p1 = jax.tree_util.tree_leaves(new_states[name].params)[0]
        assert not np.allclose(p0, p1), f"{name} did not update"
    # host pool integration
    task.host_update(outputs)
    prepared2 = task.host_prepare({"image_a": a, "image_b": b})
    assert float(prepared2["pool_valid"]) == 1.0
    assert prepared2["pool_a2b"].shape == (2, 32, 32, 3)


@pytest.mark.slow
def test_adversarial_trainer_smoke(tmp_path):
    from deep_vision_tpu.core.adversarial import AdversarialTrainer
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.data.gan import GANLoader, mnist_gan_data

    cfg = get_config("dcgan")
    cfg.batch_size = 8
    cfg.total_epochs = 1
    cfg.checkpoint_every_epochs = 1
    cfg.log_every_steps = 2
    images = mnist_gan_data(None, n_synthetic=24)
    loader = GANLoader(images, cfg.batch_size)
    task = DCGANTask(DCGANGenerator(), DCGANDiscriminator(), latent_dim=8)
    trainer = AdversarialTrainer(cfg, task, workdir=str(tmp_path))
    states = trainer.fit(loader, epochs=1)
    assert set(states) == {"generator", "discriminator"}
    # checkpoint written and resumable
    assert trainer.checkpointer.latest_step() is not None
    trainer2 = AdversarialTrainer(cfg, task, workdir=str(tmp_path))
    states2 = trainer2.init_states(next(iter(loader)))
    restored, extras = trainer2.checkpointer.restore_tree(states2)
    assert extras["epoch"] == 1
    # samples come out image-shaped
    img = task.sample(states, 2, jax.random.PRNGKey(1))
    assert img.shape == (2, 28, 28, 1)


@pytest.mark.slow
def test_adversarial_scan_steps_dcgan(tmp_path):
    """DCGAN (scan_safe) under scan_steps=2: 5 batches → 2 scanned groups
    + 1 ragged per-step tail, guard sees every step, losses stay finite."""
    from deep_vision_tpu.core.adversarial import AdversarialTrainer
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.data.gan import GANLoader, mnist_gan_data

    cfg = get_config("dcgan")
    cfg.batch_size = 8
    cfg.total_epochs = 1
    cfg.checkpoint_every_epochs = 1
    cfg.log_every_steps = 1
    cfg.scan_steps = 2
    images = mnist_gan_data(None, n_synthetic=40)  # 5 batches of 8
    loader = GANLoader(images, cfg.batch_size)
    task = DCGANTask(DCGANGenerator(), DCGANDiscriminator(), latent_dim=8)
    trainer = AdversarialTrainer(cfg, task, workdir=str(tmp_path))
    g0 = jax.device_get(
        trainer.init_states(next(iter(loader)))["generator"].params)
    states = trainer.fit(loader, epochs=1)
    # both nets updated and finite after scanned training
    g1 = jax.device_get(states["generator"].params)
    diff = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(a - b).max()), g0, g1)
    assert max(jax.tree_util.tree_leaves(diff)) > 0
    for leaf in jax.tree_util.tree_leaves(jax.device_get(states)):
        assert np.all(np.isfinite(np.asarray(leaf, np.float64)))

    # rng threads through the scan carry with the per-step split order,
    # so scan_steps=2 must train IDENTICALLY to scan_steps=1
    cfg1 = get_config("dcgan")
    cfg1.batch_size = 8
    cfg1.total_epochs = 1
    cfg1.checkpoint_every_epochs = 1000
    cfg1.log_every_steps = 1000
    cfg1.scan_steps = 1
    task1 = DCGANTask(DCGANGenerator(), DCGANDiscriminator(), latent_dim=8)
    t1 = AdversarialTrainer(cfg1, task1, workdir=str(tmp_path / "s1"))
    s1 = t1.fit(GANLoader(images, cfg1.batch_size), epochs=1)
    a = jax.device_get(s1["generator"].params)
    b = jax.device_get(states["generator"].params)
    # same rng stream, same batches; tolerance covers scan-vs-unrolled
    # XLA float reassociation through Adam only (observed max |d| ~1e-5
    # over 5 steps; a stream mismatch would diverge everywhere at O(1e-3))
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(x, y, atol=1e-4), a, b)


def test_cyclegan_not_scan_safe():
    from deep_vision_tpu.tasks.gan import CycleGANTask, DCGANTask

    assert DCGANTask.scan_safe and not CycleGANTask.scan_safe
