"""Gradient accumulation (config.grad_accum_steps): the full recipe
batch on a fraction of the HBM — the TPU answer to the reference's
shrink-the-batch OOM workarounds (ResNet/pytorch/train.py:141-148, VGG
README "batch 128→64 mid-run")."""

import jax
import numpy as np
import pytest

from deep_vision_tpu.core.config import get_config
from deep_vision_tpu.core.trainer import Trainer
from deep_vision_tpu.data.loader import ArrayLoader
from deep_vision_tpu.data.mnist import synthetic_mnist
from deep_vision_tpu.tasks.classification import ClassificationTask


def _trainer(tmp_path, mesh, accum, batch=32):
    cfg = get_config("lenet5")  # BN-free: accumulation is exact
    cfg.total_epochs = 1
    cfg.batch_size = batch
    cfg.grad_accum_steps = accum
    return cfg, Trainer(cfg, cfg.model(), ClassificationTask(10),
                        mesh=mesh, workdir=str(tmp_path))


def test_accum_matches_full_batch(tmp_path, mesh1):
    """Mean-reduced loss ⇒ averaged microbatch grads == full-batch grads:
    one step at grad_accum_steps=4 must land on the SAME params as one
    plain step on the same batch (BN-free model, exact up to f32
    reduction order)."""
    data = synthetic_mnist(32)
    batch = next(iter(ArrayLoader(data, 32, shuffle=False)))

    _, t1 = _trainer(tmp_path / "full", mesh1, 1)
    _, t4 = _trainer(tmp_path / "accum", mesh1, 4)
    s1 = t1.init_state(batch)
    s4 = t4.init_state(batch)
    # identical init (same seed/config)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        jax.device_get(s1.params), jax.device_get(s4.params))

    s1, m1 = t1.train_step(s1, dict(batch))
    s4, m4 = t4.train_step(s4, dict(batch))
    np.testing.assert_allclose(float(m4["loss"]), float(m1["loss"]),
                               rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=2e-6),
        jax.device_get(s4.params), jax.device_get(s1.params))


def test_accum_rejects_indivisible_batch(tmp_path, mesh1):
    data = synthetic_mnist(32)
    batch = next(iter(ArrayLoader(data, 32, shuffle=False)))
    _, t = _trainer(tmp_path, mesh1, 3)
    state = t.init_state(batch)
    with pytest.raises(ValueError, match="grad_accum_steps"):
        t.train_step(state, dict(batch))


def test_accum_rejected_for_adversarial(tmp_path, mesh1):
    """The AdversarialTrainer updates G and D from one forward; a silent
    no-accum run would betray the flag's promise, so it refuses."""
    from deep_vision_tpu.core.adversarial import AdversarialTrainer

    cfg = get_config("dcgan")
    cfg.grad_accum_steps = 2
    with pytest.raises(NotImplementedError, match="grad_accum"):
        AdversarialTrainer(cfg, task=None, mesh=mesh1,
                           workdir=str(tmp_path))


@pytest.mark.slow
def test_accum_trains_sharded_with_bn(tmp_path, mesh8):
    """grad_accum under an 8-way data mesh with a BN model (resnet toy):
    microbatch BN stats thread sequentially, steps stay finite, the
    guard sees no bad steps."""
    from deep_vision_tpu.data.synthetic import synthetic_classification
    from deep_vision_tpu.models.resnet import BasicBlock, ResNet

    cfg = get_config("lenet5")
    cfg.total_epochs = 1
    cfg.batch_size = 32
    cfg.grad_accum_steps = 2
    model = ResNet(stage_sizes=(1, 1), block_cls=BasicBlock,
                   num_classes=10)
    trainer = Trainer(cfg, model, ClassificationTask(10), mesh=mesh8,
                      workdir=str(tmp_path))
    data = synthetic_classification(64, 32, 3, 10)
    loader = ArrayLoader(data, 32, seed=0)
    state = trainer.fit(loader)
    assert int(jax.device_get(state.step)) == 2
    assert int(jax.device_get(state.bad_steps)) == 0
    for leaf in jax.tree_util.tree_leaves(jax.device_get(state.params)):
        assert np.all(np.isfinite(leaf))
